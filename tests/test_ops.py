"""Unit tests for core ops: masking, PE, length regulation, bucketize."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from speakingstyle_tpu.ops.length_regulator import length_regulate, predicted_durations
from speakingstyle_tpu.ops.masking import length_to_mask, masked_mean
from speakingstyle_tpu.ops.positional import sinusoid_position_table
from speakingstyle_tpu.ops.quantize import bucketize, make_bins


def test_length_to_mask():
    m = length_to_mask(jnp.array([3, 1]), 4)
    assert m.tolist() == [[False, False, False, True], [False, True, True, True]]


def test_masked_mean_matches_select_mean():
    v = jnp.array([1.0, 2.0, 3.0, 100.0])
    keep = jnp.array([True, True, True, False])
    assert float(masked_mean(v, keep)) == pytest.approx(2.0)


def test_sinusoid_table_reference_formula():
    # reference: transformer/Models.py:10-30
    t = sinusoid_position_table(8, 6)
    pos, j = 3, 4
    expected_sin = np.sin(pos / np.power(10000, 2 * (j // 2) / 6))
    assert t[pos, j] == pytest.approx(expected_sin, abs=1e-6)
    expected_cos = np.cos(pos / np.power(10000, 2 * (5 // 2) / 6))
    assert t[pos, 5] == pytest.approx(expected_cos, abs=1e-6)
    assert np.all(t[0, 0::2] == 0.0) and np.all(t[0, 1::2] == 1.0)


def test_length_regulate_expands_per_duration():
    # phoneme i repeated durations[i] times, like the reference Python loop
    # (reference: model/modules.py:174-197)
    x = jnp.arange(1, 4, dtype=jnp.float32)[None, :, None]  # [1,3,1] values 1,2,3
    d = jnp.array([[2, 0, 3]])
    frames, mel_lens, pad = length_regulate(x, d, 7)
    assert mel_lens.tolist() == [5]
    assert frames[0, :, 0].tolist() == [1, 1, 3, 3, 3, 0, 0]
    assert pad[0].tolist() == [False] * 5 + [True] * 2


def test_length_regulate_truncates_to_budget():
    x = jnp.ones((1, 2, 4))
    d = jnp.array([[5, 5]])
    frames, mel_lens, pad = length_regulate(x, d, 6)
    assert mel_lens.tolist() == [6]
    assert not bool(pad.any())


def test_length_regulate_jits():
    f = jax.jit(length_regulate, static_argnums=2)
    x = jnp.ones((2, 3, 4))
    d = jnp.array([[1, 2, 3], [0, 0, 1]])
    frames, mel_lens, pad = f(x, d, 8)
    assert frames.shape == (2, 8, 4)
    assert mel_lens.tolist() == [6, 1]


def test_predicted_durations_round_then_scale():
    # round(exp(logd)-1) * control, clamped at 0 (reference: modules.py:137-144)
    logd = jnp.log(jnp.array([[4.0, 1.0, 0.1]]))  # exp-1 = 3, 0, -0.9
    mask = jnp.array([[False, False, False]])
    assert predicted_durations(logd, mask, 1.0).tolist() == [[3, 0, 0]]
    assert predicted_durations(logd, mask, 2.0).tolist() == [[6, 0, 0]]
    mask2 = jnp.array([[False, False, True]])
    assert predicted_durations(logd, mask2, 1.0)[0, 2] == 0


def test_bucketize_matches_torch_semantics():
    # torch.bucketize(v, [0,1,2]) == [0,0,1,1,2,3] for v=[-1,0,.5,1,2,3]
    bins = np.array([0.0, 1.0, 2.0], np.float32)
    v = jnp.array([-1.0, 0.0, 0.5, 1.0, 2.0, 3.0])
    assert bucketize(v, bins).tolist() == [0, 0, 1, 1, 2, 3]


def test_make_bins():
    lin = make_bins(0.0, 10.0, 6, "linear")
    assert lin.shape == (5,) and lin[0] == 0.0 and lin[-1] == 10.0
    log = make_bins(1.0, 100.0, 5, "log")
    assert log[0] == pytest.approx(1.0) and log[-1] == pytest.approx(100.0)


def test_grad_reverse():
    """Identity forward; -alpha * g backward (reference: model/blocks.py:7-40)."""
    import jax
    import jax.numpy as jnp

    from speakingstyle_tpu.ops.grad_reverse import grad_reverse

    x = jnp.asarray([1.0, -2.0, 3.0])
    np.testing.assert_array_equal(np.asarray(grad_reverse(x, 0.7)), np.asarray(x))

    g = jax.grad(lambda x: (grad_reverse(x, 0.7) ** 2).sum())(x)
    np.testing.assert_allclose(np.asarray(g), -0.7 * 2 * np.asarray(x), rtol=1e-6)
    # jits and composes with other grads
    g2 = jax.jit(jax.grad(lambda x: grad_reverse(x, 2.0).sum() + x.sum()))(x)
    np.testing.assert_allclose(np.asarray(g2), np.full(3, -2.0 + 1.0), rtol=1e-6)


# ---------------------------------------------------------------------------
# dropout impls (ops/dropout.py)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("impl", ["bernoulli", "bits16", "hash"])
def test_dropout_impls(impl):
    """Every mask impl: correct keep rate, inverted scaling, determinism
    per key, decorrelation across keys, and exact zeros at drops."""
    import jax

    from speakingstyle_tpu.ops.dropout import dropout, keep_mask

    rate = 0.2
    shape = (65, 97, 33)  # odd element count: exercises the bits16 tail slice
    k1, k2 = jax.random.PRNGKey(3), jax.random.PRNGKey(4)
    m1 = np.asarray(keep_mask(k1, rate, shape, impl))
    m1b = np.asarray(keep_mask(k1, rate, shape, impl))
    m2 = np.asarray(keep_mask(k2, rate, shape, impl))
    assert m1.shape == shape and m1.dtype == bool
    np.testing.assert_array_equal(m1, m1b)  # deterministic per key
    assert m1.mean() == pytest.approx(1 - rate, abs=0.01)
    assert (m1 != m2).mean() > 0.2  # different keys -> different masks

    x = jnp.asarray(np.random.default_rng(0).standard_normal(shape),
                    jnp.float32)
    y = np.asarray(dropout(x, rate, k1, impl=impl))
    np.testing.assert_allclose(
        y[m1], np.asarray(x)[m1] / (1 - rate), rtol=1e-6
    )
    assert (y[~m1] == 0).all()

    # grad flows only through kept elements, scaled
    g = jax.grad(lambda x_: jnp.sum(dropout(x_, rate, k1, impl=impl)))(x)
    np.testing.assert_allclose(
        np.asarray(g), m1.astype(np.float32) / (1 - rate), rtol=1e-6
    )


def test_dropout_hash_no_spatial_structure():
    """The counter-hash mask must not correlate along any axis (the risk
    of an iota-based stream): neighboring elements' keep decisions are
    statistically independent."""
    import jax

    from speakingstyle_tpu.ops.dropout import keep_mask

    m = np.asarray(
        keep_mask(jax.random.PRNGKey(0), 0.5, (256, 256), "hash")
    ).astype(np.int8)
    # lag-1 agreement along each axis ~ 0.5 for independent bits
    for ax in (0, 1):
        a = np.take(m, range(0, m.shape[ax] - 1), axis=ax)
        b = np.take(m, range(1, m.shape[ax]), axis=ax)
        assert abs((a == b).mean() - 0.5) < 0.02
    # and across keys
    m2 = np.asarray(
        keep_mask(jax.random.PRNGKey(1), 0.5, (256, 256), "hash")
    ).astype(np.int8)
    assert abs((m == m2).mean() - 0.5) < 0.02


# ---------------------------------------------------------------------------
# conv1d lowerings (ops/conv.py, ops/pallas_conv.py) — fast parity gate
# ---------------------------------------------------------------------------

def _conv_ref(x, w, b, dilation=1):
    import jax

    y = jax.lax.conv_general_dilated(
        x, w, window_strides=(1,), padding="SAME",
        rhs_dilation=(dilation,), dimension_numbers=("NWC", "WIO", "NWC"),
    )
    return y + b


@pytest.mark.parametrize("k,dilation", [(1, 1), (3, 1), (9, 1), (3, 2), (5, 3)])
def test_conv1d_impl_parity(k, dilation):
    """unfold and pallas lowerings match lax.conv exactly (fwd + grad)."""
    import jax

    from speakingstyle_tpu.ops.conv import conv1d_unfold
    from speakingstyle_tpu.ops.pallas_conv import fused_conv1d

    rng = np.random.default_rng(k * 10 + dilation)
    x = jnp.asarray(rng.standard_normal((2, 23, 8)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((k, 8, 12)) * 0.2, jnp.float32)
    b = jnp.asarray(rng.standard_normal(12) * 0.1, jnp.float32)

    ref = _conv_ref(x, w, b, dilation)
    np.testing.assert_allclose(
        np.asarray(conv1d_unfold(x, w, b, dilation=dilation)), np.asarray(ref),
        atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(fused_conv1d(x, w, b, dilation=dilation, interpret=True)),
        np.asarray(ref), atol=1e-5,
    )

    g_ref = jax.grad(lambda x_: jnp.sum(_conv_ref(x_, w, b, dilation) ** 2))(x)
    g_unf = jax.grad(
        lambda x_: jnp.sum(conv1d_unfold(x_, w, b, dilation=dilation) ** 2)
    )(x)
    g_pal = jax.grad(
        lambda x_: jnp.sum(
            fused_conv1d(x_, w, b, dilation=dilation, interpret=True) ** 2
        )
    )(x)
    np.testing.assert_allclose(np.asarray(g_unf), np.asarray(g_ref), atol=1e-4)
    np.testing.assert_allclose(np.asarray(g_pal), np.asarray(g_ref), atol=1e-4)


def test_fused_conv_relu_ln_matches_composed():
    """The fully fused pallas path == conv -> relu -> LayerNorm, fwd + grads
    wrt every operand."""
    import jax

    from speakingstyle_tpu.ops.pallas_conv import (
        _reference_fused,
        fused_conv_relu_ln,
    )

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((2, 19, 8)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((3, 8, 16)) * 0.2, jnp.float32)
    b = jnp.asarray(rng.standard_normal(16) * 0.1, jnp.float32)
    s = jnp.asarray(rng.standard_normal(16), jnp.float32)
    sb = jnp.asarray(rng.standard_normal(16), jnp.float32)

    got = fused_conv_relu_ln(x, w, b, s, sb, interpret=True)
    want = _reference_fused(x, w, b, s, sb, 1, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    g_got = jax.grad(
        lambda a: jnp.sum(
            fused_conv_relu_ln(a[0], a[1], a[2], a[3], a[4], interpret=True) ** 2
        )
    )((x, w, b, s, sb))
    g_want = jax.grad(
        lambda a: jnp.sum(_reference_fused(a[0], a[1], a[2], a[3], a[4], 1, True) ** 2)
    )((x, w, b, s, sb))
    for gg, gw in zip(g_got, g_want):
        np.testing.assert_allclose(np.asarray(gg), np.asarray(gw), atol=1e-4)


def test_fused_conv_bwd_modes_agree():
    """Both backward modes (analytic default, recompute A/B path) produce
    the same gradients through the explicit ``bwd_mode`` argument."""
    import jax

    from speakingstyle_tpu.ops.pallas_conv import fused_conv1d

    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((2, 16, 8)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((3, 8, 12)) * 0.1, jnp.float32)
    grads = [
        np.asarray(
            jax.grad(
                lambda x_: jnp.sum(
                    fused_conv1d(
                        x_, w, None, interpret=True, bwd_mode=m
                    ) ** 2
                )
            )(x)
        )
        for m in ("analytic", "recompute")
    ]
    np.testing.assert_allclose(grads[0], grads[1], atol=1e-5)


def test_fused_conv_bwd_modes_agree_bf16():
    """Analytic-vs-recompute gradient parity with bf16 storage and ReLU.

    Tolerance note: the analytic backward rebuilds the ReLU mask from the
    activation residual *as stored in bf16* with a strictly-positive
    threshold (finfo(bf16).tiny), while recompute mode re-derives it from
    an f32 recompute. The two masks can only disagree on elements whose
    pre-activation magnitude is below bf16's smallest normal (~1.2e-38) —
    probability ~0 for these inputs — so the remaining difference is pure
    bf16 rounding noise on the matching elements, bounded by the loose
    tolerances here (bf16 has ~8 mantissa bits => ~0.4% relative)."""
    import jax

    from speakingstyle_tpu.ops.pallas_conv import fused_conv1d

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((2, 16, 8)), jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((3, 8, 12)) * 0.1, jnp.bfloat16)
    grads = [
        np.asarray(
            jax.grad(
                lambda x_: jnp.sum(
                    fused_conv1d(
                        x_, w, None, relu=True, interpret=True, bwd_mode=m
                    ).astype(jnp.float32) ** 2
                )
            )(x),
            np.float32,
        )
        for m in ("analytic", "recompute")
    ]
    np.testing.assert_allclose(grads[0], grads[1], rtol=2e-2, atol=5e-2)
    # the fix this guards: gradients flow wherever the STORED activation
    # is a normal positive — analytic mode must not zero more elements
    # than a strictly-positive stored value implies
    y = np.asarray(
        fused_conv1d(x, w, None, relu=True, interpret=True), np.float32
    )
    dy_analytic = np.asarray(
        jax.grad(
            lambda x_: jnp.sum(
                fused_conv1d(
                    x_, w, None, relu=True, interpret=True,
                    bwd_mode="analytic",
                ).astype(jnp.float32).sum(axis=(0, 1))[0]
            )
        )(x),
        np.float32,
    )
    assert np.any(y > 0) and np.any(dy_analytic != 0)


def test_fused_conv_relu_ln_grads_lane_aligned():
    """Gradient parity at a lane-aligned (cout=128) width: this is the
    config where the REAL kernel path runs (the cout=16 test above trips
    the lane-alignment fallback to the jnp reference), so it exercises the
    want_act second pallas output + analytic backward wiring in CI."""
    import jax

    from speakingstyle_tpu.ops.pallas_conv import (
        _reference_fused,
        fused_conv_relu_ln,
    )

    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.standard_normal((2, 24, 128)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((3, 128, 128)) * 0.05, jnp.float32)
    b = jnp.asarray(rng.standard_normal(128) * 0.1, jnp.float32)
    s = jnp.asarray(rng.standard_normal(128), jnp.float32)
    sb = jnp.asarray(rng.standard_normal(128), jnp.float32)

    got = fused_conv_relu_ln(x, w, b, s, sb, interpret=True)
    want = _reference_fused(x, w, b, s, sb, 1, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    g_got = jax.grad(
        lambda a: jnp.sum(
            fused_conv_relu_ln(a[0], a[1], a[2], a[3], a[4], interpret=True)
            ** 2
        )
    )((x, w, b, s, sb))
    g_want = jax.grad(
        lambda a: jnp.sum(
            _reference_fused(a[0], a[1], a[2], a[3], a[4], 1, True) ** 2
        )
    )((x, w, b, s, sb))
    for gg, gw in zip(g_got, g_want):
        np.testing.assert_allclose(
            np.asarray(gg), np.asarray(gw), rtol=2e-4, atol=2e-4
        )


def test_conv1d_module_tree_matches_nn_conv():
    """Conv1d's param entry is nn.Conv-identical for every impl."""
    import flax.linen as nn
    import jax

    from speakingstyle_tpu.ops.conv import Conv1d

    x = jnp.zeros((1, 11, 8), jnp.float32)
    want = jax.tree_util.tree_map(
        jnp.shape,
        nn.Conv(12, kernel_size=(5,), padding="SAME").init(
            jax.random.PRNGKey(0), x
        )["params"],
    )
    for impl in ("xla", "unfold", "pallas"):
        got = jax.tree_util.tree_map(
            jnp.shape,
            Conv1d(12, kernel_size=5, impl=impl).init(
                jax.random.PRNGKey(0), x
            )["params"],
        )
        assert got == want, impl


@pytest.mark.parametrize("L,H,D", [(23, 4, 16), (130, 2, 8)])
def test_fused_mha_matches_einsum(L, H, D):
    """The fused attention kernel (interpret mode) matches the einsum
    reference — forward and q/k/v gradients — including padding-mask
    handling and the T -> multiple-of-128 internal padding."""
    import jax

    from speakingstyle_tpu.ops.pallas_attention import _reference_mha, fused_mha

    rng = np.random.default_rng(L + H + D)
    B = 2
    q = jnp.asarray(rng.standard_normal((B, L, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, L, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, L, H, D)), jnp.float32)
    lens = rng.integers(L // 2, L + 1, B)
    mask = jnp.asarray(np.arange(L)[None] >= lens[:, None])
    real = jnp.where(mask, 0.0, 1.0)[:, :, None, None]

    sm = 1.0 / np.sqrt(D)
    out = fused_mha(q, k, v, mask, interpret=True)
    ref = _reference_mha(q, k, v, mask, sm, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(out * real), np.asarray(ref * real), atol=1e-5
    )

    def loss(f):
        return lambda q_, k_, v_: jnp.sum(jnp.square(f(q_, k_, v_) * real))

    g_fused = jax.grad(
        loss(lambda q_, k_, v_: fused_mha(q_, k_, v_, mask, interpret=True)),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        loss(lambda q_, k_, v_: _reference_mha(q_, k_, v_, mask, sm, jnp.float32)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_fused_mha_unsupported_shapes_fall_back():
    """Head dim > 128 / not multiple of 8 and very long T use the einsum
    reference instead of the kernel (exact equality — same code path)."""
    from speakingstyle_tpu.ops.pallas_attention import (
        _reference_mha,
        fused_mha,
        supported,
    )

    assert not supported(600, 20)      # D % 8 != 0
    assert not supported(600, 256)     # D > lane width
    assert not supported(2000, 64)     # T too long for VMEM scores
    assert supported(600, 32) and supported(1000, 128)
    # sub-4-byte dtypes pack 2 rows/sublane: D must be a multiple of 16
    assert not supported(600, 24, jnp.bfloat16)
    assert not supported(600, 8, jnp.bfloat16)
    assert supported(600, 32, jnp.bfloat16)
    assert supported(600, 24)  # ...but f32 allows %8

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((2, 9, 2, 20)), jnp.float32)
    mask = jnp.zeros((2, 9), bool)
    out = fused_mha(q, q, q, mask, interpret=True)
    ref = _reference_mha(q, q, q, mask, 1.0 / np.sqrt(20), jnp.float32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=0)


def test_model_attention_kernel_knob():
    """attention_kernel="fused" at the model level: same param tree as
    einsum (the kernel is parameter-free) and matching outputs on CPU
    (where the fused path falls back to the identical einsum reference)."""
    import dataclasses

    import jax

    from tests.test_models import make_batch, tiny_config
    from speakingstyle_tpu.models.fastspeech2 import FastSpeech2

    cfg_e = tiny_config(attention_kernel="einsum")  # default is now fused
    cfg_f = dataclasses.replace(
        cfg_e, model=dataclasses.replace(cfg_e.model, attention_kernel="fused")
    )
    texts, src_lens, mels, mel_lens, p, e, d = make_batch()
    speakers = jnp.zeros((2,), jnp.int32)
    kwargs = dict(
        mels=mels, mel_lens=mel_lens, max_mel_len=18,
        p_targets=p, e_targets=e, d_targets=d, deterministic=True,
    )
    outs = {}
    trees = {}
    for label, cfg in (("einsum", cfg_e), ("fused", cfg_f)):
        m = FastSpeech2(config=cfg, pitch_stats=(-2, 8), energy_stats=(-1, 9))
        variables = m.init(
            jax.random.PRNGKey(0), speakers, texts, src_lens, **kwargs
        )
        trees[label] = jax.tree_util.tree_map(jnp.shape, variables["params"])
        out, _ = m.apply(
            variables, speakers, texts, src_lens, **kwargs,
            mutable=["batch_stats"],
        )
        outs[label] = np.asarray(out["mel"])
    assert trees["einsum"] == trees["fused"]
    np.testing.assert_allclose(outs["einsum"], outs["fused"], atol=1e-5)
