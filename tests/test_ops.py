"""Unit tests for core ops: masking, PE, length regulation, bucketize."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from speakingstyle_tpu.ops.length_regulator import length_regulate, predicted_durations
from speakingstyle_tpu.ops.masking import length_to_mask, masked_mean
from speakingstyle_tpu.ops.positional import sinusoid_position_table
from speakingstyle_tpu.ops.quantize import bucketize, make_bins


def test_length_to_mask():
    m = length_to_mask(jnp.array([3, 1]), 4)
    assert m.tolist() == [[False, False, False, True], [False, True, True, True]]


def test_masked_mean_matches_select_mean():
    v = jnp.array([1.0, 2.0, 3.0, 100.0])
    keep = jnp.array([True, True, True, False])
    assert float(masked_mean(v, keep)) == pytest.approx(2.0)


def test_sinusoid_table_reference_formula():
    # reference: transformer/Models.py:10-30
    t = sinusoid_position_table(8, 6)
    pos, j = 3, 4
    expected_sin = np.sin(pos / np.power(10000, 2 * (j // 2) / 6))
    assert t[pos, j] == pytest.approx(expected_sin, abs=1e-6)
    expected_cos = np.cos(pos / np.power(10000, 2 * (5 // 2) / 6))
    assert t[pos, 5] == pytest.approx(expected_cos, abs=1e-6)
    assert np.all(t[0, 0::2] == 0.0) and np.all(t[0, 1::2] == 1.0)


def test_length_regulate_expands_per_duration():
    # phoneme i repeated durations[i] times, like the reference Python loop
    # (reference: model/modules.py:174-197)
    x = jnp.arange(1, 4, dtype=jnp.float32)[None, :, None]  # [1,3,1] values 1,2,3
    d = jnp.array([[2, 0, 3]])
    frames, mel_lens, pad = length_regulate(x, d, 7)
    assert mel_lens.tolist() == [5]
    assert frames[0, :, 0].tolist() == [1, 1, 3, 3, 3, 0, 0]
    assert pad[0].tolist() == [False] * 5 + [True] * 2


def test_length_regulate_truncates_to_budget():
    x = jnp.ones((1, 2, 4))
    d = jnp.array([[5, 5]])
    frames, mel_lens, pad = length_regulate(x, d, 6)
    assert mel_lens.tolist() == [6]
    assert not bool(pad.any())


def test_length_regulate_jits():
    f = jax.jit(length_regulate, static_argnums=2)
    x = jnp.ones((2, 3, 4))
    d = jnp.array([[1, 2, 3], [0, 0, 1]])
    frames, mel_lens, pad = f(x, d, 8)
    assert frames.shape == (2, 8, 4)
    assert mel_lens.tolist() == [6, 1]


def test_predicted_durations_round_then_scale():
    # round(exp(logd)-1) * control, clamped at 0 (reference: modules.py:137-144)
    logd = jnp.log(jnp.array([[4.0, 1.0, 0.1]]))  # exp-1 = 3, 0, -0.9
    mask = jnp.array([[False, False, False]])
    assert predicted_durations(logd, mask, 1.0).tolist() == [[3, 0, 0]]
    assert predicted_durations(logd, mask, 2.0).tolist() == [[6, 0, 0]]
    mask2 = jnp.array([[False, False, True]])
    assert predicted_durations(logd, mask2, 1.0)[0, 2] == 0


def test_bucketize_matches_torch_semantics():
    # torch.bucketize(v, [0,1,2]) == [0,0,1,1,2,3] for v=[-1,0,.5,1,2,3]
    bins = np.array([0.0, 1.0, 2.0], np.float32)
    v = jnp.array([-1.0, 0.0, 0.5, 1.0, 2.0, 3.0])
    assert bucketize(v, bins).tolist() == [0, 0, 1, 1, 2, 3]


def test_make_bins():
    lin = make_bins(0.0, 10.0, 6, "linear")
    assert lin.shape == (5,) and lin[0] == 0.0 and lin[-1] == 10.0
    log = make_bins(1.0, 100.0, 5, "log")
    assert log[0] == pytest.approx(1.0) and log[-1] == pytest.approx(100.0)


def test_grad_reverse():
    """Identity forward; -alpha * g backward (reference: model/blocks.py:7-40)."""
    import jax
    import jax.numpy as jnp

    from speakingstyle_tpu.ops.grad_reverse import grad_reverse

    x = jnp.asarray([1.0, -2.0, 3.0])
    np.testing.assert_array_equal(np.asarray(grad_reverse(x, 0.7)), np.asarray(x))

    g = jax.grad(lambda x: (grad_reverse(x, 0.7) ** 2).sum())(x)
    np.testing.assert_allclose(np.asarray(g), -0.7 * 2 * np.asarray(x), rtol=1e-6)
    # jits and composes with other grads
    g2 = jax.jit(jax.grad(lambda x: grad_reverse(x, 2.0).sum() + x.sum()))(x)
    np.testing.assert_allclose(np.asarray(g2), np.full(3, -2.0 + 1.0), rtol=1e-6)
