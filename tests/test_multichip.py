"""Multichip training (ISSUE 10), on the 8-virtual-device CPU mesh.

Covers the config->mesh resolution layer (train.parallel.*), the
structured batch-divisibility gate, cross-mesh-shape checkpoint resume
(save on mesh A, restore onto mesh B, bit-identically), the shard-local
nan_grads drill against the dp-reduced NaN sentinel, and per-device
observability gauges during a mesh train smoke.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from speakingstyle_tpu.configs.config import (
    ParallelConfig,
    PathConfig,
    StepConfig,
    TrainPathConfig,
    load_config,
)
from speakingstyle_tpu.parallel import (
    BatchShardingError,
    local_batch_size,
    make_mesh,
    resolve_mesh,
)
from speakingstyle_tpu.parallel.partition import (
    parse_rule_overrides,
    train_state_shardings,
)
from speakingstyle_tpu.training import CheckpointManager, TrainState, run_training
from speakingstyle_tpu.training import faults


# ---------------------------------------------------------------------------
# 1. config -> mesh resolution (train.parallel.*)
# ---------------------------------------------------------------------------


def test_parallel_config_validation():
    ParallelConfig(mesh=[4, 2], seq=1)  # valid
    with pytest.raises(ValueError):
        ParallelConfig(mesh=[8])  # must be [dp, tp]
    with pytest.raises(ValueError):
        ParallelConfig(mesh=[4, 0])  # tp >= 1
    with pytest.raises(ValueError):
        ParallelConfig(mesh=[-2, 1])  # dp >= 1 or -1
    with pytest.raises(ValueError):
        ParallelConfig(partition_rules=[["kernel", "none,ring"]])  # bad axis
    with pytest.raises(ValueError):
        ParallelConfig(partition_rules=[["(unclosed", "none,model"]])


def test_resolve_mesh_single_chip_is_none():
    # [1,1] must leave the single-chip path byte-for-byte intact
    assert resolve_mesh(ParallelConfig()) is None
    assert resolve_mesh(ParallelConfig(mesh=[1, 1])) is None


def test_resolve_mesh_shapes():
    mesh = resolve_mesh(ParallelConfig(mesh=[8, 1]))
    assert mesh.shape["data"] == 8 and mesh.shape["model"] == 1
    # dp=-1: all remaining devices after tp
    mesh = resolve_mesh(ParallelConfig(mesh=[-1, 2]))
    assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2


def test_resolve_mesh_too_many_devices_names_the_fix():
    with pytest.raises(ValueError, match="xla_force_host_platform_device_count"):
        resolve_mesh(ParallelConfig(mesh=[16, 1]))


def test_local_batch_size_structured_error():
    with pytest.raises(BatchShardingError) as exc:
        local_batch_size(12, make_mesh())  # 12 over dp=8
    msg = str(exc.value)
    assert "12" in msg and "dp=8" in msg and "8x1" in msg
    assert "8 or 16" in msg  # the two nearest valid batch sizes


def test_parse_rule_overrides_prepend():
    rules = parse_rule_overrides([["foo/kernel", "none,model"]])
    pat, spec = rules[0]
    assert pat == "foo/kernel" and spec == P(None, "model")


# ---------------------------------------------------------------------------
# 2. cross-mesh-shape resume: save on A, restore onto B, bit-identical
# ---------------------------------------------------------------------------

# (dp, tp); None = the production 1x1 path (no mesh at all)
_MESHES = {"1x1": None, "8x1": (8, 1), "4x2": (4, 2)}
# the toy kernel is named to match this TP override rule (rules are
# re.match-anchored full-path regexes over the flattened param paths)
_TP_RULES = [["dense/kernel", "none,model"]]


def _mk_mesh(spec):
    if spec is None:
        return None
    dp, tp = spec
    return make_mesh(data=dp, model=tp, devices=jax.devices()[: dp * tp])


def _toy_state(tx):
    variables = {
        "params": {
            "dense": {
                "kernel": jnp.arange(128, dtype=jnp.float32).reshape(8, 16),
                "bias": jnp.linspace(0.0, 1.0, 16, dtype=jnp.float32),
            }
        },
        "batch_stats": {},
    }
    return TrainState.create(variables, tx)


def _lay_out(state, mesh):
    """The trainer's layout rule: TP shardings when the model axis is >1,
    replicated on a pure-DP mesh, plain host/single-device state at 1x1."""
    if mesh is None:
        return state, None
    if mesh.shape["model"] > 1:
        sh = train_state_shardings(state, mesh, parse_rule_overrides(_TP_RULES))
        return jax.tree_util.tree_map(jax.device_put, state, sh), sh
    return jax.device_put(state, NamedSharding(mesh, P())), None


def _advance(state, tx):
    """One optimizer step with unit grads (makes opt_state non-trivial)."""
    grads = jax.tree_util.tree_map(jnp.ones_like, state.params)
    updates, new_opt = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return state.replace(
        step=state.step + 1, params=params, opt_state=new_opt
    )


@pytest.mark.parametrize(
    "src,dst",
    [("8x1", "4x2"), ("8x1", "1x1"), ("4x2", "8x1"), ("1x1", "4x2")],
)
def test_cross_mesh_resume_bit_identical(tmp_path, src, dst):
    tx = optax.adam(1e-3)
    state, _ = _lay_out(_toy_state(tx), _mk_mesh(_MESHES[src]))
    state = _advance(state, tx)  # adam moments become non-trivial
    ckpt = CheckpointManager(str(tmp_path / "ck"))
    ckpt.save(1, state, block=True)

    mesh_b = _mk_mesh(_MESHES[dst])
    target, _ = _lay_out(_toy_state(tx), mesh_b)
    restored = ckpt.restore(target, step=1)
    ckpt.close()

    # every leaf — params AND optimizer state — survives bit-identically
    want = jax.tree_util.tree_leaves(jax.device_get(state))
    got = jax.tree_util.tree_leaves(jax.device_get(restored))
    assert len(want) == len(got)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(np.asarray(w), np.asarray(g))

    # the restored state landed in the TARGET layout, not the source's
    if mesh_b is not None and mesh_b.shape["model"] > 1:
        spec = restored.params["dense"]["kernel"].sharding.spec
        assert "model" in str(spec), spec

    # ... and one optimizer step runs in that layout
    stepped = jax.jit(lambda s: _advance(s, tx))(restored)
    assert int(stepped.step) == 2
    assert np.isfinite(np.asarray(jax.device_get(
        stepped.params["dense"]["kernel"]))).all()


def test_restore_via_sharded_abstract(tmp_path):
    """The no-materialization spelling: restore against
    TrainState.sharded_abstract over the target mesh's shardings."""
    tx = optax.adam(1e-3)
    state, _ = _lay_out(_toy_state(tx), _mk_mesh(_MESHES["8x1"]))
    state = _advance(state, tx)
    ckpt = CheckpointManager(str(tmp_path / "ck"))
    ckpt.save(1, state, block=True)

    mesh_b = _mk_mesh(_MESHES["4x2"])
    template = _toy_state(tx)
    sh = train_state_shardings(
        template, mesh_b, parse_rule_overrides(_TP_RULES)
    )
    restored = ckpt.restore(template.sharded_abstract(sh), step=1)
    ckpt.close()
    assert "model" in str(restored.params["dense"]["kernel"].sharding.spec)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(restored.params["dense"]["kernel"])),
        np.asarray(jax.device_get(state.params["dense"]["kernel"])),
    )


# ---------------------------------------------------------------------------
# 3. the shard-local nan_grads drill against the dp-reduced sentinel
# ---------------------------------------------------------------------------


def test_dp_poison_rows_arithmetic():
    assert faults.dp_poison_rows(8, 8) == 1  # one shard's rows
    assert faults.dp_poison_rows(8, 1) == 8  # no mesh: whole batch
    assert faults.dp_poison_rows(16, 4) == 4
    assert faults.dp_poison_rows(4, 8) == 4  # degenerate: keep full batch


def test_shard_local_poison_trips_flag_on_every_device():
    """Inject NaN on ONE dp shard; the all-reduced ``_finite`` flag must
    read False — replicated — on all 8 devices."""
    from tests.test_parallel import _tiny_batch, _tiny_cfg

    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.training import make_optimizer, make_train_step

    mesh = make_mesh()  # 8x1 pure DP
    cfg = _tiny_cfg()
    model = build_model(cfg)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    tx = make_optimizer(cfg.train)
    state = jax.device_put(
        TrainState.create(variables, tx), NamedSharding(mesh, P())
    )
    step = make_train_step(model, tx, cfg, mesh=mesh)

    batch = _tiny_batch(mesh)  # B=8 over dp=8: one row per shard
    poisoned = faults.poison_batch(batch, mesh=mesh)
    # the poison is shard-local: row 0 only, sharding preserved
    host_mels = np.asarray(jax.device_get(poisoned["mels"]))
    assert np.isnan(host_mels[0]).any()
    assert np.isfinite(host_mels[1:]).all()
    assert poisoned["mels"].sharding == batch["mels"].sharding

    # control first (the step donates its input state): clean flag is True
    state, clean_losses = step(state, batch, jax.random.PRNGKey(1))
    assert bool(clean_losses["_finite"])

    _, losses = step(state, poisoned, jax.random.PRNGKey(1))
    flag = losses["_finite"]
    assert not bool(flag)
    assert flag.sharding.is_fully_replicated
    # identical verdict on EVERY device, not just the poisoned shard's
    shard_vals = [bool(s.data) for s in flag.addressable_shards]
    assert shard_vals == [False] * 8


# ---------------------------------------------------------------------------
# 4. run_training on the config mesh: rollback drill + per-device gauges
# ---------------------------------------------------------------------------


def _mesh_train_config(root, tmp_path, mesh=(8, 1), batch_size=8):
    cfg = load_config(preset="LJSpeech")
    tf = dataclasses.replace(
        cfg.model.transformer,
        encoder_layer=1, decoder_layer=1, encoder_hidden=16,
        decoder_hidden=16, encoder_head=2, decoder_head=2,
        conv_filter_size=32,
    )
    ref = dataclasses.replace(
        cfg.model.reference_encoder,
        encoder_layer=1, encoder_hidden=16, conv_layer=1,
        conv_filter_size=32, encoder_head=2,
    )
    vp = dataclasses.replace(cfg.model.variance_predictor, filter_size=16)
    mc = dataclasses.replace(
        cfg.model, transformer=tf, reference_encoder=ref,
        variance_predictor=vp, max_seq_len=128, compute_dtype="float32",
    )
    pp = dataclasses.replace(
        cfg.preprocess, path=PathConfig(preprocessed_path=root)
    )
    opt = dataclasses.replace(cfg.train.optimizer, batch_size=batch_size)
    steps = StepConfig(
        total_step=6, log_step=1, synth_step=10**9, val_step=10**9,
        save_step=2,
    )
    paths = TrainPathConfig(
        ckpt_path=str(tmp_path / "ckpt"),
        log_path=str(tmp_path / "log"),
        result_path=str(tmp_path / "res"),
    )
    tr = dataclasses.replace(
        cfg.train, optimizer=opt, step=steps, path=paths,
        parallel=ParallelConfig(mesh=list(mesh)),
    )
    return dataclasses.replace(cfg, preprocess=pp, model=mc, train=tr)


def test_run_training_rejects_indivisible_batch(synthetic_preprocessed,
                                                tmp_path):
    """The startup gate: batch 10 over dp=8 is a structured config error
    (named batch, mesh shape, nearest valid sizes), not a shard crash."""
    cfg = _mesh_train_config(
        synthetic_preprocessed, tmp_path, mesh=(8, 1), batch_size=10
    )
    with pytest.raises(BatchShardingError, match="8 or 16"):
        run_training(cfg, max_steps=1)


def test_mesh_train_smoke_nan_rollback_and_per_device_gauges(
    synthetic_preprocessed, tmp_path, monkeypatch
):
    """One drill, three acceptance criteria: run_training resolves the
    8x1 mesh from train.parallel alone; the shard-local nan_grads fault
    trips the dp-reduced sentinel into the same rollback as single-chip;
    and the per-device MFU/memory gauges land in the registry snapshot."""
    from speakingstyle_tpu.obs import get_registry

    monkeypatch.setenv(faults.ENV_VAR, "nan_grads@3")
    cfg = _mesh_train_config(synthetic_preprocessed, tmp_path, mesh=(8, 1))
    state = run_training(cfg, max_steps=6)  # mesh comes from the config
    assert int(state.step) == 6

    log = (tmp_path / "log" / "log.txt").read_text()
    assert "non-finite losses/grads at step 3" in log
    assert "rollback 1/3 to checkpoint step 2" in log

    snap = get_registry().snapshot()["gauges"]
    labels = [f'train_achieved_flops_per_sec{{device="cpu:{i}"}}'
              for i in range(8)]
    assert all(k in snap for k in labels), sorted(snap)
    assert all(snap[k] > 0 for k in labels)
    mem = [k for k in snap
           if k.startswith('device_memory_watermark_bytes{device="cpu:')]
    assert len(mem) == 8 and all(snap[k] > 0 for k in mem), sorted(snap)
