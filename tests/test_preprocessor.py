"""Offline preprocessing: TextGrid parsing, F0, alignment, full corpus build."""

import json
import os

import numpy as np
import pytest

from speakingstyle_tpu.configs.config import (
    Config,
    PathConfig,
    PreprocessConfig,
    PreprocessingConfig,
)
from speakingstyle_tpu.data.f0 import yin_f0
from speakingstyle_tpu.data.preprocessor import (
    Preprocessor,
    RunningScaler,
    get_alignment,
    interpolate_unvoiced,
    phoneme_average,
    remove_outliers,
)
from speakingstyle_tpu.data.textgrid import parse_textgrid

SR, HOP = 22050, 256


# ---------------------------------------------------------------------------
# TextGrid parser
# ---------------------------------------------------------------------------

LONG_TG = """File type = "ooTextFile"
Object class = "TextGrid"

xmin = 0
xmax = 1.0
tiers? <exists>
size = 2
item []:
    item [1]:
        class = "IntervalTier"
        name = "words"
        xmin = 0
        xmax = 1.0
        intervals: size = 2
        intervals [1]:
            xmin = 0
            xmax = 0.5
            text = "hello"
        intervals [2]:
            xmin = 0.5
            xmax = 1.0
            text = ""
    item [2]:
        class = "IntervalTier"
        name = "phones"
        xmin = 0
        xmax = 1.0
        intervals: size = 3
        intervals [1]:
            xmin = 0
            xmax = 0.2
            text = "HH"
        intervals [2]:
            xmin = 0.2
            xmax = 0.5
            text = "AH0"
        intervals [3]:
            xmin = 0.5
            xmax = 1.0
            text = "sp"
"""

SHORT_TG = """File type = "ooTextFile"
Object class = "TextGrid"

0
1.0
<exists>
1
"IntervalTier"
"phones"
0
1.0
2
0
0.6
"AA1"
0.6
1.0
"sil"
"""


def test_parse_long_textgrid():
    tg = parse_textgrid(LONG_TG)
    assert tg.xmax == 1.0
    assert set(tg.tiers) == {"words", "phones"}
    phones = tg.get_tier("phones")
    assert phones == [(0.0, 0.2, "HH"), (0.2, 0.5, "AH0"), (0.5, 1.0, "sp")]


def test_parse_short_textgrid():
    tg = parse_textgrid(SHORT_TG)
    assert tg.get_tier("phones") == [(0.0, 0.6, "AA1"), (0.6, 1.0, "sil")]


def test_parse_textgrid_quoted_escapes():
    tg = parse_textgrid(LONG_TG.replace('"hello"', '"say ""hi"""'))
    assert tg.get_tier("words")[0][2] == 'say "hi"'


def test_get_tier_missing_raises():
    with pytest.raises(KeyError):
        parse_textgrid(SHORT_TG).get_tier("words")


# ---------------------------------------------------------------------------
# Alignment (silence trimming, hop-unit durations)
# ---------------------------------------------------------------------------

def test_get_alignment_trims_silences():
    intervals = [
        (0.0, 0.1, "sil"),   # leading silence dropped
        (0.1, 0.3, "HH"),
        (0.3, 0.4, "sp"),    # internal silence kept
        (0.4, 0.6, "AH0"),
        (0.6, 1.0, "sil"),   # trailing silence dropped
    ]
    phones, durations, start, end = get_alignment(intervals, SR, HOP)
    assert phones == ["HH", "sp", "AH0"]
    assert start == pytest.approx(0.1) and end == pytest.approx(0.6)
    # durations sum to the hop count of [start, end)
    total = round(0.6 * SR / HOP) - round(0.1 * SR / HOP)
    assert sum(durations) == total
    assert all(d >= 0 for d in durations)


def test_get_alignment_all_silence():
    phones, durations, start, end = get_alignment([(0.0, 1.0, "sp")], SR, HOP)
    assert phones == [] and durations == []


# ---------------------------------------------------------------------------
# Feature post-processing
# ---------------------------------------------------------------------------

def test_phoneme_average_matches_loop():
    rng = np.random.default_rng(0)
    durations = [3, 0, 5, 2]
    values = rng.standard_normal(sum(durations))
    out = phoneme_average(values, durations)
    # reference loop semantics (preprocessor.py:209-228)
    pos, expected = 0, []
    for d in durations:
        expected.append(values[pos : pos + d].mean() if d > 0 else 0.0)
        pos += d
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_interpolate_unvoiced():
    p = np.array([0.0, 100.0, 0.0, 0.0, 130.0, 0.0])
    out = interpolate_unvoiced(p)
    np.testing.assert_allclose(out, [100, 100, 110, 120, 130, 130])


def test_remove_outliers():
    vals = np.array([1.0, 1.1, 0.9, 1.05, 50.0])
    out = remove_outliers(vals)
    assert 50.0 not in out and len(out) == 4


def test_running_scaler_matches_numpy():
    rng = np.random.default_rng(1)
    chunks = [rng.standard_normal(n) * 3 + 7 for n in (10, 50, 1)]
    sc = RunningScaler()
    for c in chunks:
        sc.partial_fit(c)
    allv = np.concatenate(chunks)
    assert sc.mean == pytest.approx(allv.mean(), rel=1e-9)
    assert sc.std == pytest.approx(allv.std(), rel=1e-9)


def _cents(est, true):
    return 1200.0 * np.log2(est / true)


def test_yin_f0_sine_and_silence():
    t = np.arange(SR) / SR
    wav = 0.5 * np.sin(2 * np.pi * 220.0 * t)
    f0 = yin_f0(wav, SR, HOP)
    voiced = f0[f0 > 0]
    assert len(voiced) > 0.9 * len(f0)
    assert np.median(voiced) == pytest.approx(220.0, rel=0.02)
    assert (yin_f0(np.zeros(SR), SR, HOP) == 0).all()


def test_yin_f0_cents_accuracy_pure_tones():
    """Accuracy bound for the pyworld-replacing YIN tracker (data/f0.py).

    pyworld (the reference's F0 backend, reference:
    preprocessor/preprocessor.py:182-187) is not installable here, so
    instead of bounding YIN-vs-pyworld disagreement we bound YIN against
    analytic ground truth — a stronger statement. Measured on this host:
    median well under 1 cent per tone; max <30 cents at the lowest pitch
    (long-lag quantization).
    """
    t = np.arange(SR) / SR
    for f in (82.4, 110.0, 220.0, 440.0, 660.0):
        f0 = yin_f0(0.4 * np.sin(2 * np.pi * f * t), SR, HOP)
        voiced = f0[f0 > 0]
        assert len(voiced) > 0.9 * len(f0)
        c = np.abs(_cents(voiced, f))
        assert np.median(c) < 2.0, f
        assert c.max() < 35.0, f


def test_yin_f0_tracks_glide():
    t = np.arange(SR) / SR
    f_inst = 120.0 * 2.0**t  # one octave per second
    wav = 0.4 * np.sin(2 * np.pi * np.cumsum(f_inst) / SR)
    f0 = yin_f0(wav, SR, HOP)
    frames_t = np.arange(len(f0)) * HOP / SR
    true = 120.0 * 2.0**frames_t
    mask = (f0 > 0) & (frames_t < 0.95)
    assert mask.sum() > 0.85 * len(f0)
    c = np.abs(_cents(f0[mask], true[mask]))
    assert np.median(c) < 2.0 and np.percentile(c, 95) < 20.0


def test_yin_f0_speechlike_utterance():
    """Synthetic utterance: 130 Hz glottal pulse train with 5 Hz vibrato
    through three formant resonators — the closest analogue to a real
    utterance with exactly known F0. Bound: >=90% voiced recall, median
    error <5 cents, p95 <20 cents, <5% gross (octave-class) errors."""
    from scipy.signal import lfilter

    t = np.arange(SR) / SR
    f_mean, vib = 130.0, 0.03
    f_inst = f_mean * (1 + vib * np.sin(2 * np.pi * 5 * t))
    phase = np.cumsum(f_inst) / SR
    wav = (np.diff(np.floor(phase), prepend=0.0) > 0).astype(float)
    for fc, bw in ((500, 80), (1500, 120), (2500, 160)):
        r = np.exp(-np.pi * bw / SR)
        wav = lfilter(
            [1.0], [1, -2 * r * np.cos(2 * np.pi * fc / SR), r * r], wav
        )
    wav = 0.3 * wav / np.abs(wav).max()
    wav += 0.001 * np.random.default_rng(0).standard_normal(len(wav))

    f0 = yin_f0(wav, SR, HOP)
    frames_t = np.arange(len(f0)) * HOP / SR
    true = f_mean * (1 + vib * np.sin(2 * np.pi * 5 * frames_t))
    mask = f0 > 0
    assert mask.mean() > 0.9
    c = np.abs(_cents(f0[mask], true[mask]))
    assert np.median(c) < 5.0
    assert np.percentile(c, 95) < 20.0
    assert (c > 100.0).mean() < 0.05  # octave-class gross errors


def test_yin_f0_unvoiced_rejection_and_boundaries():
    rng = np.random.default_rng(1)
    assert (yin_f0(0.1 * rng.standard_normal(SR), SR, HOP) == 0).all()

    n2 = SR // 2
    wav = np.concatenate([
        0.4 * np.sin(2 * np.pi * 200 * np.arange(n2) / SR),
        np.zeros(n2),
        0.4 * np.sin(2 * np.pi * 300 * np.arange(n2) / SR),
    ])
    f0 = yin_f0(wav, SR, HOP)
    n = len(f0)
    assert (f0[int(0.05 * n):int(0.28 * n)] > 0).all()
    assert (f0[int(0.38 * n):int(0.60 * n)] == 0).all()
    assert (f0[int(0.72 * n):int(0.95 * n)] > 0).all()


def test_yin_f0_matches_pyworld_when_available():
    """Direct YIN-vs-DIO+StoneMask agreement — runs wherever pyworld IS
    installed (the env spec's `preprocess` extra), so features built there
    are proven interchangeable with reference-built ones."""
    pw = pytest.importorskip("pyworld")
    t = np.arange(2 * SR) / SR
    f_inst = 150.0 * (1 + 0.05 * np.sin(2 * np.pi * 3 * t))
    wav = 0.4 * np.sin(2 * np.pi * np.cumsum(f_inst) / SR)
    ours = yin_f0(wav, SR, HOP)
    ref, tt = pw.dio(wav.astype(np.float64), SR, frame_period=HOP / SR * 1000)
    ref = pw.stonemask(wav.astype(np.float64), ref, tt, SR)
    m = min(len(ours), len(ref))
    ours, ref = ours[:m], ref[:m]
    both = (ours > 0) & (ref > 0)
    assert (ours > 0).mean() == pytest.approx((ref > 0).mean(), abs=0.1)
    c = np.abs(_cents(ours[both], ref[both]))
    assert np.median(c) < 10.0 and np.percentile(c, 95) < 50.0


# ---------------------------------------------------------------------------
# End-to-end corpus build on a synthetic mini-corpus
# ---------------------------------------------------------------------------

def _write_textgrid(path, phone_spans):
    n = len(phone_spans)
    xmax = phone_spans[-1][1]
    body = [
        'File type = "ooTextFile"',
        'Object class = "TextGrid"',
        "",
        "xmin = 0",
        f"xmax = {xmax}",
        "tiers? <exists>",
        "size = 1",
        "item []:",
        "    item [1]:",
        '        class = "IntervalTier"',
        '        name = "phones"',
        "        xmin = 0",
        f"        xmax = {xmax}",
        f"        intervals: size = {n}",
    ]
    for i, (s, e, p) in enumerate(phone_spans, 1):
        body += [
            f"        intervals [{i}]:",
            f"            xmin = {s}",
            f"            xmax = {e}",
            f'            text = "{p}"',
        ]
    with open(path, "w") as f:
        f.write("\n".join(body) + "\n")


def _make_corpus(root, n_utts=3):
    import scipy.io.wavfile

    raw = os.path.join(root, "raw")
    out = os.path.join(root, "preprocessed")
    spk = "S1"
    os.makedirs(os.path.join(raw, spk))
    os.makedirs(os.path.join(out, "TextGrid", spk))
    rng = np.random.default_rng(0)
    for i in range(n_utts):
        dur = 1.2
        t = np.arange(int(SR * dur)) / SR
        hz = 160 + 40 * i
        wav = 0.4 * np.sin(2 * np.pi * hz * t) + 0.01 * rng.standard_normal(len(t))
        pcm = (wav * 32000).astype(np.int16)
        scipy.io.wavfile.write(os.path.join(raw, spk, f"u{i}.wav"), SR, pcm)
        with open(os.path.join(raw, spk, f"u{i}.lab"), "w") as f:
            f.write(f"utterance {i}")
        _write_textgrid(
            os.path.join(out, "TextGrid", spk, f"u{i}.TextGrid"),
            [
                (0.0, 0.1, "sil"),
                (0.1, 0.5, "HH"),
                (0.5, 0.7, "AH0"),
                (0.7, 1.0, "L"),
                (1.0, dur, "sil"),
            ],
        )
    return raw, out


def test_preprocessor_end_to_end(tmp_path):
    raw, out = _make_corpus(tmp_path)
    cfg = Config(
        preprocess=PreprocessConfig(
            dataset="LJSpeech",
            path=PathConfig(raw_path=raw, preprocessed_path=out),
            preprocessing=PreprocessingConfig(val_size=1),
        )
    )
    lines = Preprocessor(cfg).build_from_path(num_workers=1)
    assert len(lines) == 3
    base, speaker, text, raw_text = lines[0].split("|")
    assert speaker == "S1" and text.startswith("{") and text.endswith("}")

    stats = json.load(open(os.path.join(out, "stats.json")))
    assert set(stats) == {"pitch", "energy"}
    for k in ("pitch", "energy"):
        vmin, vmax, mean, std = stats[k]
        assert vmin < vmax and std > 0

    speakers = json.load(open(os.path.join(out, "speakers.json")))
    assert speakers == {"S1": 0}

    train = open(os.path.join(out, "train.txt")).read().splitlines()
    val = open(os.path.join(out, "val.txt")).read().splitlines()
    assert len(train) == 2 and len(val) == 1

    # features exist, shapes consistent: len(pitch) == len(duration) for
    # phoneme-level; mel frames == sum(duration)
    b = train[0].split("|")[0]
    d = np.load(os.path.join(out, "duration", f"S1-duration-{b}.npy"))
    p = np.load(os.path.join(out, "pitch", f"S1-pitch-{b}.npy"))
    e = np.load(os.path.join(out, "energy", f"S1-energy-{b}.npy"))
    m = np.load(os.path.join(out, "mel", f"S1-mel-{b}.npy"))
    assert len(p) == len(d) == len(e) == 3  # HH, AH0, L
    assert m.shape == (int(d.sum()), 80)
    # normalized features: roughly zero-mean across corpus
    assert abs(float(p.mean())) < 3.0


def test_preprocessor_multiprocessing(tmp_path):
    raw, out = _make_corpus(tmp_path)
    cfg = Config(
        preprocess=PreprocessConfig(
            path=PathConfig(raw_path=raw, preprocessed_path=out),
            preprocessing=PreprocessingConfig(val_size=1),
        )
    )
    lines = Preprocessor(cfg).build_from_path(num_workers=2)
    assert len(lines) == 3


def test_preprocessor_trains_downstream(tmp_path):
    """The preprocessor's output is directly consumable by SpeechDataset."""
    from speakingstyle_tpu.data import BucketedBatcher, SpeechDataset

    raw, out = _make_corpus(tmp_path)
    cfg = Config(
        preprocess=PreprocessConfig(
            path=PathConfig(raw_path=raw, preprocessed_path=out),
            preprocessing=PreprocessingConfig(val_size=1),
        )
    )
    Preprocessor(cfg).build_from_path(num_workers=1)
    ds = SpeechDataset("train.txt", cfg, sort=False, drop_last=False)
    assert len(ds) == 2
    batcher = BucketedBatcher(ds, max_src=64, max_mel=256)
    batch = next(batcher.epoch(shuffle=False))
    arrays = batch.arrays()
    assert arrays["mels"].shape[-1] == 80
    assert (arrays["durations"].sum(axis=1)[: batch.n_real]
            == arrays["mel_lens"][: batch.n_real]).all()


# ---------------------------------------------------------------------------
# Corpus adapters
# ---------------------------------------------------------------------------

def test_ljspeech_prepare_align(tmp_path):
    import scipy.io.wavfile

    from speakingstyle_tpu.data.corpora import prepare_align

    corpus = tmp_path / "LJSpeech-1.1"
    (corpus / "wavs").mkdir(parents=True)
    rng = np.random.default_rng(0)
    names = ["LJ001-0001", "LJ001-0002"]
    for name in names:
        wav = (rng.standard_normal(SR // 2) * 3000).astype(np.int16)
        scipy.io.wavfile.write(corpus / "wavs" / f"{name}.wav", SR, wav)
    (corpus / "metadata.csv").write_text(
        "LJ001-0001|raw one|Printing, two words.\n"
        "LJ001-0002|raw two|Number 42 here.\n"
    )
    raw = tmp_path / "raw"
    cfg = Config(
        preprocess=PreprocessConfig(
            dataset="LJSpeech",
            path=PathConfig(corpus_path=str(corpus), raw_path=str(raw)),
        )
    )
    prepare_align(cfg)
    for name in names:
        assert (raw / "LJSpeech" / f"{name}.wav").exists()
    lab = (raw / "LJSpeech" / "LJ001-0002.lab").read_text()
    assert "forty" in lab and "42" not in lab  # cleaner expanded the number
    sr, pcm = __import__("scipy.io.wavfile", fromlist=["read"]).read(
        raw / "LJSpeech" / "LJ001-0001.wav"
    )
    assert sr == SR and pcm.dtype == np.int16


def test_phoneme_average_values_shorter_than_durations():
    """Boundary rounding can leave fewer frames than sum(durations); the
    averaging must clamp against the real frame count, not sum(durations)-1
    (regression: IndexError aborted corpus builds late)."""
    durations = [3, 4, 2]           # sum = 9
    values = np.arange(7.0)         # 2 frames short
    out = phoneme_average(values, durations)
    assert out.shape == (3,)
    assert np.isfinite(out).all()
    np.testing.assert_allclose(out[0], values[0:3].mean())


def test_phoneme_average_empty_values():
    out = phoneme_average(np.zeros(0), [2, 3])
    np.testing.assert_allclose(out, [0.0, 0.0])


def test_normalize_dir_empty_written_is_finite(tmp_path):
    """stats.json must stay valid JSON when a run writes zero feature files
    (regression: (inf, -inf) serialized as Infinity)."""
    out = str(tmp_path / "pre")
    os.makedirs(os.path.join(out, "pitch"))
    cfg = Config(
        preprocess=PreprocessConfig(
            path=PathConfig(raw_path=str(tmp_path), preprocessed_path=out),
        )
    )
    vmin, vmax = Preprocessor(cfg)._normalize_dir("pitch", 0.0, 1.0, [])
    assert np.isfinite(vmin) and np.isfinite(vmax)
    json.dumps({"pitch": [vmin, vmax]})  # must not raise / emit Infinity


def test_native_yin_matches_numpy():
    """The C++ YIN (speakingstyle_tpu/native) is an exact port of the
    numpy tracker: identical voiced mask, |Δf0| at float-noise level."""
    from speakingstyle_tpu.native import have_native_yin, yin_f0_native

    if not have_native_yin():
        pytest.skip("no C++ compiler available")
    rng = np.random.default_rng(0)
    t = np.arange(2 * SR) / SR
    f_inst = 150.0 * (1 + 0.05 * np.sin(2 * np.pi * 3 * t))
    wav = 0.4 * np.sin(2 * np.pi * np.cumsum(f_inst) / SR)
    wav += 0.002 * rng.standard_normal(len(t))

    a = yin_f0(wav, SR, HOP)
    b = yin_f0_native(wav, SR, HOP)
    assert a.shape == b.shape
    np.testing.assert_array_equal(a > 0, b > 0)
    both = (a > 0) & (b > 0)
    assert np.abs(a[both] - b[both]).max() < 1e-6

    # silence/noise paths agree too
    np.testing.assert_array_equal(
        yin_f0_native(np.zeros(SR), SR, HOP) > 0, np.zeros(SR // HOP + 1, bool)
    )


def test_extract_f0_backend_chain():
    """extract_f0 without pyworld lands on the native (or numpy) YIN and
    keeps the contract: len(wav)//hop + 1 frames, zeros on unvoiced."""
    from speakingstyle_tpu.data.f0 import extract_f0

    t = np.arange(SR) / SR
    wav = 0.4 * np.sin(2 * np.pi * 220.0 * t)
    f0 = extract_f0(wav, SR, HOP)
    assert len(f0) == SR // HOP + 1
    voiced = f0[f0 > 0]
    assert len(voiced) > 0.8 * len(f0)
    assert np.median(voiced) == pytest.approx(220.0, rel=0.02)
