"""bench.py --compare: the regression gate over recorded bench artifacts.

Pure-host tests (no jax): artifact-metric extraction across both stored
formats (driver records with "parsed"/"tail", raw JSON-lines) and the
threshold/exit-code contract of the diff table.
"""

import importlib.util
import io
import json
import os

import pytest


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_lines(path, records):
    with open(path, "w") as fh:
        for rec in records:
            fh.write(json.dumps(rec) + "\n")
    return str(path)


_OLD = [
    {"metric": "train_mel_frames_per_sec", "value": 400_000.0,
     "unit": "mel-frames/sec/chip", "vs_baseline": 1.6},
    {"metric": "serve_offered_load", "clients": 8, "qps": 100.0,
     "p50_ms": 40.0, "p95_ms": 80.0, "p99_ms": 120.0},
    {"metric": "serve_speedup_vs_sequential", "value": 4.8},
]


def test_artifact_metrics_from_json_lines(bench, tmp_path):
    path = _write_lines(tmp_path / "old.json", _OLD)
    m = bench._artifact_metrics(path)
    assert m["train_mel_frames_per_sec"] == (400_000.0, "higher")
    assert m["serve_qps_8c"] == (100.0, "higher")
    assert m["serve_p95_ms_8c"] == (80.0, "lower")
    assert m["serve_speedup_vs_sequential"] == (4.8, "higher")


def test_artifact_metrics_from_driver_record(bench, tmp_path):
    """The BENCH_r*.json trajectory format: one driver dict whose
    "parsed" holds the headline line and "tail" the raw stdout; null
    values (guarded failures) are skipped."""
    rec = {
        "n": 5,
        "cmd": "python bench.py",
        "rc": 0,
        "tail": json.dumps(_OLD[1]) + "\n" + json.dumps(_OLD[2]) + "\n",
        "parsed": _OLD[0],
    }
    path = tmp_path / "driver.json"
    path.write_text(json.dumps(rec))
    m = bench._artifact_metrics(str(path))
    assert m["train_mel_frames_per_sec"] == (400_000.0, "higher")
    assert m["serve_qps_8c"] == (100.0, "higher")

    null = dict(rec, parsed={"metric": "train_mel_frames_per_sec",
                             "value": None, "error": "timeout"}, tail="")
    path.write_text(json.dumps(null))
    assert bench._artifact_metrics(str(path)) == {}


def test_compare_ok_within_threshold(bench, tmp_path):
    old = _write_lines(tmp_path / "old.json", _OLD)
    new = _write_lines(tmp_path / "new.json", [
        dict(_OLD[0], value=390_000.0),          # -2.5%: fine
        dict(_OLD[1], qps=105.0, p95_ms=84.0),   # +5% qps, +5% p95: fine
        _OLD[2],
    ])
    out = io.StringIO()
    assert bench.run_compare(old, new, out=out) == 0
    text = out.getvalue()
    assert "OK" in text and "REGRESSION" not in text
    assert "train_mel_frames_per_sec" in text


def test_compare_fails_on_throughput_regression(bench, tmp_path):
    old = _write_lines(tmp_path / "old.json", _OLD)
    new = _write_lines(tmp_path / "new.json", [
        dict(_OLD[0], value=300_000.0),  # -25%: regression
        _OLD[1],
        _OLD[2],
    ])
    out = io.StringIO()
    assert bench.run_compare(old, new, out=out) == 1
    text = out.getvalue()
    assert "REGRESSION" in text and "FAIL" in text
    assert "train_mel_frames_per_sec" in text


def test_compare_fails_on_latency_regression(bench, tmp_path):
    """Latency is lower-is-better: a p95 that RISES past the threshold
    fails even while every throughput number holds."""
    old = _write_lines(tmp_path / "old.json", _OLD)
    new = _write_lines(tmp_path / "new.json", [
        _OLD[0],
        dict(_OLD[1], p95_ms=120.0),  # +50% p95
        _OLD[2],
    ])
    out = io.StringIO()
    assert bench.run_compare(old, new, out=out) == 1
    assert "serve_p95_ms_8c" in out.getvalue()


def test_compare_no_common_metrics_is_usage_error(bench, tmp_path):
    old = _write_lines(tmp_path / "old.json", _OLD)
    new = _write_lines(tmp_path / "new.json",
                       [{"metric": "something_else", "value": 1.0}])
    out = io.StringIO()
    assert bench.run_compare(old, new, out=out) == 2


def test_compare_threshold_is_tunable(bench, tmp_path):
    old = _write_lines(tmp_path / "old.json", _OLD)
    new = _write_lines(tmp_path / "new.json", [dict(_OLD[0], value=380_000.0)])
    out = io.StringIO()
    assert bench.run_compare(old, new, threshold=0.10, out=out) == 0  # -5%
    assert bench.run_compare(old, new, threshold=0.02, out=out) == 1
