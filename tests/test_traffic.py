"""Traffic model + closed-loop autoscaler (tier-1).

Three layers, mirroring the new subsystem:
  1. TrafficModel — seeded determinism (same seed, identical schedule),
     rate-curve shape (diurnal floor/peak, 10x flash windows), priority
     mix riding the router's existing SLO classes, zipf style skew —
     all host-only, no clock, no jax;
  2. Autoscaler policy — driven synchronously against a fake router
     with an explicit clock: scale-up on queue pressure / occupancy /
     shed-pressure, per-direction cooldowns, max_step at extreme
     pressure, hard [min, max] bounds, scale-down only after a calm
     window stretched by the MEASURED warm-up cost, decision
     observability (gauge + reason counter + autoscale events);
  3. closed-loop e2e — a flash crowd against a real FleetRouter with
     fake engines grows the fleet without operator input, recovers, and
     shrinks back, with ZERO lost requests and zero compiles.
"""

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from speakingstyle_tpu.configs.config import (
    AutoscaleConfig,
    Config,
    FleetConfig,
    ServeConfig,
)
from speakingstyle_tpu.obs import MetricsRegistry
from speakingstyle_tpu.serving.autoscale import Autoscaler
from speakingstyle_tpu.serving.batcher import Overloaded, ShutdownError
from speakingstyle_tpu.serving.fleet import FleetRouter
from speakingstyle_tpu.serving.traffic import TrafficEvent, TrafficModel

# ---------------------------------------------------------------------------
# traffic model (no jax, no clock)
# ---------------------------------------------------------------------------


def _model(**kw):
    args = dict(seed=7, base_qps=50.0, duration_s=6.0,
                flash_windows=[(2.0, 4.0)], flash_multiplier=10.0,
                n_styles=32)
    args.update(kw)
    return TrafficModel(**args)


def test_traffic_same_seed_identical_schedule():
    a, b = _model().schedule(), _model().schedule()
    assert a == b                       # bit-identical events
    assert _model().schedule() == a     # and stable across calls
    assert a and all(isinstance(e, TrafficEvent) for e in a)


def test_traffic_different_seed_differs():
    assert _model().schedule() != _model(seed=8).schedule()


def test_traffic_rate_curve_shape():
    m = _model(diurnal_floor=0.4)
    # diurnal: trough at t=0, peak mid-period
    assert m.diurnal_at(0.0) == pytest.approx(0.4)
    assert m.diurnal_at(3.0) == pytest.approx(1.0)
    # flash multiplies the diurnal rate inside the window only
    assert m.rate_at(3.0) == pytest.approx(10.0 * m.base_qps)
    assert m.rate_at(1.0) < m.base_qps
    # empirical arrivals track the curve: the flash window holds most
    # of the schedule despite covering a third of the duration
    sched = m.schedule()
    in_flash = sum(2.0 <= e.t < 4.0 for e in sched)
    assert in_flash / len(sched) > 0.6
    assert all(0.0 <= e.t < m.duration_s for e in sched)
    assert all(sched[i].t <= sched[i + 1].t for i in range(len(sched) - 1))


def test_traffic_mix_rides_existing_priority_classes():
    sched = _model(duration_s=20.0, flash_windows=[]).schedule()
    kinds = {e.kind for e in sched}
    assert kinds == {"interactive", "batch", "long_form"}
    # long-form rides the batch SLO class and carries CHAPTER lengths —
    # multiples of the interactive ceiling, i.e. work only the long-form
    # endpoint (serving/longform.py) can admit
    for e in sched:
        assert e.priority in ("interactive", "batch")
        if e.kind == "long_form":
            assert e.priority == "batch" and 2.0 <= e.length_frac <= 8.0
        else:
            assert 0.0 < e.length_frac < 1.0
    frac_interactive = sum(
        e.kind == "interactive" for e in sched) / len(sched)
    assert 0.45 < frac_interactive < 0.75  # ~0.6 by weight


def test_traffic_zipf_styles_are_skewed_and_bounded():
    sched = _model(duration_s=30.0, flash_windows=[], n_styles=16).schedule()
    styles = [e.style for e in sched]
    assert all(0 <= s < 16 for s in styles)
    counts = np.bincount(styles, minlength=16)
    # rank 0 is the hottest voice and the tail is still visited
    assert counts[0] == counts.max()
    assert counts[0] > 3 * counts[8:].mean()
    assert (counts > 0).sum() >= 8


def test_traffic_validation():
    with pytest.raises(ValueError, match="base_qps"):
        _model(base_qps=0)
    with pytest.raises(ValueError, match="flash window"):
        _model(flash_windows=[(5.0, 99.0)])
    with pytest.raises(ValueError, match="flash_multiplier"):
        _model(flash_multiplier=0.5)
    with pytest.raises(ValueError, match="unknown traffic kinds"):
        _model(mix={"interactive": 1.0, "cinematic": 1.0})
    with pytest.raises(ValueError, match="zipf_s"):
        _model(zipf_s=0.0)
    assert "seed" in _model().describe()


# ---------------------------------------------------------------------------
# autoscaler policy (fake router, explicit clock)
# ---------------------------------------------------------------------------


class FakeRouter:
    """Signal-surface stand-in: the policy's entire view of the fleet."""

    def __init__(self, queue_depth=100, replicas=1):
        self.fleet = SimpleNamespace(queue_depth=queue_depth)
        self.registry = MetricsRegistry()
        self.events = None
        self.depth = 0
        self.occ = 0.0
        self.live = replicas
        self.warmup = None
        self.scale_calls = []
        self.closed = False

    def pending_depth(self):
        return self.depth

    def live_replica_count(self):
        return self.live

    def occupancy(self):
        return self.occ

    def warmup_cost_s(self):
        return self.warmup

    def scale_to(self, n):
        if self.closed:
            raise ShutdownError("router is closed")
        self.scale_calls.append(n)
        self.live = n


class FakeEvents:
    def __init__(self):
        self.records = []

    def emit(self, name, **fields):
        self.records.append((name, fields))


def _acfg(**kw):
    args = dict(enabled=True, min_replicas=1, max_replicas=4,
                interval_s=0.1, up_queue_fraction=0.5, up_occupancy=0.9,
                up_pressure_rate=1.0, down_queue_fraction=0.05,
                down_occupancy=0.5, down_stable_s=1.0, cooldown_up_s=2.0,
                cooldown_down_s=3.0, max_step=2, assumed_warmup_s=10.0,
                warmup_cost_factor=1.0)
    args.update(kw)
    return AutoscaleConfig(**args)


def test_autoscale_config_validation():
    with pytest.raises(ValueError, match="min_replicas"):
        AutoscaleConfig(min_replicas=0)
    with pytest.raises(ValueError, match="max_replicas"):
        AutoscaleConfig(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="down_queue_fraction"):
        AutoscaleConfig(up_queue_fraction=0.3, down_queue_fraction=0.4)
    with pytest.raises(ValueError, match="down_occupancy"):
        AutoscaleConfig(up_occupancy=0.8, down_occupancy=0.9)
    with pytest.raises(ValueError, match="max_step"):
        AutoscaleConfig(max_step=0)
    # disabled by default: arming is an explicit config decision
    assert ServeConfig().autoscale.enabled is False


def test_autoscaler_scales_up_on_queue_pressure_with_cooldown():
    router = FakeRouter(queue_depth=100)
    events = FakeEvents()
    scaler = Autoscaler(router, _acfg(), events=events, start=False)
    router.depth = 50                      # at the up watermark
    assert scaler.step(now=100.0) == "queue_depth"
    assert router.scale_calls == [2]
    # still under pressure but inside cooldown_up_s: hold
    assert scaler.step(now=101.0) is None
    assert router.scale_calls == [2]
    # cooldown elapsed: grow again
    assert scaler.step(now=102.5) == "queue_depth"
    assert router.scale_calls == [2, 3]
    # observability: gauge, reason counter, events with signal values
    assert router.registry.value("serve_autoscale_target") == 3
    assert router.registry.value("serve_autoscale_decisions_total",
                                 {"reason": "queue_depth"}) == 2
    names = [n for n, _ in events.records]
    assert names == ["autoscale", "autoscale"]
    rec = events.records[0][1]
    assert rec["decision"] == "up" and rec["reason"] == "queue_depth"
    assert rec["depth"] == 50 and rec["target"] == 2


def test_autoscaler_max_step_at_extreme_pressure_and_max_bound():
    router = FakeRouter(queue_depth=100)
    scaler = Autoscaler(router, _acfg(max_step=2), start=False)
    router.depth = 100                     # past twice the up watermark
    assert scaler.step(now=100.0) == "queue_depth"
    assert router.scale_calls == [3]       # 1 + max_step
    assert scaler.step(now=103.0) == "queue_depth"
    assert router.scale_calls == [3, 4]    # clamped to max_replicas
    # saturated: pressure can never push past the bound
    for i in range(5):
        assert scaler.step(now=110.0 + 3.0 * i) is None
    assert router.scale_calls == [3, 4]
    assert max(router.scale_calls) <= 4


def test_autoscaler_occupancy_needs_sustained_backlog():
    router = FakeRouter(queue_depth=100, replicas=2)
    scaler = Autoscaler(router, _acfg(interval_s=0.5), start=False)
    router.occ = 1.0                       # fully busy ...
    router.depth = 1                       # ... but barely any backlog
    assert scaler.step(now=100.0) is None  # right-sized: hold
    router.depth = 2                       # one pending per live replica
    assert scaler.step(now=101.0) is None  # first hot sample: not yet
    assert scaler.step(now=101.6) == "occupancy"  # held a full tick
    assert router.scale_calls == [3]
    # a cool sample between two hot ones resets the persistence window:
    # one mid-dispatch snapshot must not buy a replica
    router.depth = 0
    assert scaler.step(now=104.0) is None
    router.depth = 3
    assert scaler.step(now=104.5) is None  # hot again, streak restarted
    assert scaler.step(now=105.1) == "occupancy"
    assert router.scale_calls == [3, 4]
    # on a ONE-replica fleet a single queued request is batch-formation
    # latency, not pressure: the backlog gate floors at 2
    solo = FakeRouter(queue_depth=100, replicas=1)
    lone = Autoscaler(solo, _acfg(interval_s=0.5), start=False)
    solo.occ = 1.0
    solo.depth = 1
    for i in range(4):
        assert lone.step(now=200.0 + 0.6 * i) is None
    assert solo.scale_calls == []


def test_autoscaler_pressure_rate_trigger():
    router = FakeRouter(queue_depth=100)
    scaler = Autoscaler(router, _acfg(up_pressure_rate=5.0), start=False)
    assert scaler.step(now=100.0) is None
    shed = router.registry.counter("serve_shed_total")
    router.registry.counter("serve_deadline_miss_total",
                            labels={"class": "interactive"}).inc(2)
    shed.inc(2)                            # 4 events over 1 s: under rate
    assert scaler.step(now=101.0) is None
    shed.inc(6)                            # 6 events over 1 s: over rate
    assert scaler.step(now=102.0) == "pressure"
    assert router.scale_calls == [2]


def test_autoscaler_scale_down_waits_for_measured_warmup_window():
    router = FakeRouter(queue_depth=100, replicas=3)
    scaler = Autoscaler(
        router,
        _acfg(down_stable_s=1.0, cooldown_down_s=1.0, warmup_cost_factor=2.0),
        start=False,
    )
    router.warmup = 4.0    # measured p50: calm window = max(1, 2*4) = 8 s
    assert scaler.step(now=100.0) is None  # calm starts
    assert scaler.step(now=104.0) is None  # 4 s calm < 8 s required
    assert scaler.step(now=108.5) == "calm"
    assert router.scale_calls == [2]
    # the streak restarts after a shed: another full window before -1
    assert scaler.step(now=109.0) is None
    assert scaler.step(now=117.0) == "calm"
    assert router.scale_calls == [2, 1]
    # at min_replicas: calm never drains below the floor
    for i in range(4):
        assert scaler.step(now=120.0 + 9.0 * i) is None
    assert router.live == 1
    # unmeasured cost model: assumed_warmup_s stands in
    router.warmup = None
    assert scaler.warmup_cost_s() == 10.0


def test_autoscaler_pressure_resets_calm_streak():
    router = FakeRouter(queue_depth=100, replicas=2)
    scaler = Autoscaler(router, _acfg(down_stable_s=1.0, cooldown_down_s=0.0,
                                      warmup_cost_factor=0.0), start=False)
    assert scaler.step(now=100.0) is None      # calm begins
    router.depth = 60
    # pressure interrupts the calm streak: the fleet grows instead
    assert scaler.step(now=100.5) == "queue_depth"
    assert router.scale_calls == [3]
    router.depth = 0
    assert scaler.step(now=101.0) is None      # calm restarts here
    assert scaler.step(now=101.8) is None      # 0.8 s < down_stable_s
    assert scaler.step(now=102.1) == "calm"
    assert router.scale_calls == [3, 2]


def test_autoscaler_bound_enforcement_and_closed_router():
    router = FakeRouter(queue_depth=100, replicas=0)
    scaler = Autoscaler(router, _acfg(min_replicas=2), start=False)
    assert scaler.step(now=100.0) == "min_bound"
    assert router.scale_calls == [2]
    router.live = 9
    assert scaler.step(now=100.1) == "max_bound"
    assert router.scale_calls == [2, 4]
    router.closed = True
    router.live = 0
    assert scaler.step(now=100.2) is None      # ShutdownError swallowed
    scaler.close()


def test_autoscaler_thread_is_stop_aware():
    router = FakeRouter(queue_depth=100)
    scaler = Autoscaler(router, _acfg(interval_s=30.0), start=True)
    t0 = time.monotonic()
    scaler.close()                         # must not wait out the tick
    assert time.monotonic() - t0 < 5.0
    assert scaler._thread is None


# ---------------------------------------------------------------------------
# closed-loop e2e: flash crowd -> grow -> recover -> shrink (fake engines)
# ---------------------------------------------------------------------------


class SlowEngine:
    """Replica stand-in with a real service time, so capacity is finite
    and a flash crowd actually queues."""

    def __init__(self, service_s=0.02):
        self.service_s = service_s

    def precompile(self):
        return 0.0

    def run(self, requests):
        time.sleep(self.service_s)
        return [SimpleNamespace(id=r.id, bucket=None, mel_len=1)
                for r in requests]


def _req(i, **kw):
    from speakingstyle_tpu.serving.engine import SynthesisRequest

    return SynthesisRequest(
        id=f"t{i}", sequence=np.ones(8, np.int32),
        ref_mel=np.zeros((4, 80), np.float32), **kw,
    )


def test_autoscaler_closed_loop_flash_crowd():
    from speakingstyle_tpu.serving.engine import CompileMonitor

    cfg = Config(serve=ServeConfig(
        batch_buckets=[1], src_buckets=[16], mel_buckets=[64],
        frames_per_phoneme=2, max_wait_ms=1.0,
        fleet=FleetConfig(
            queue_depth=16, stream_window=8,
            class_deadline_ms={"interactive": 60_000.0,
                               "batch": 120_000.0},
        ),
        autoscale=AutoscaleConfig(
            enabled=True, min_replicas=1, max_replicas=3,
            interval_s=0.02, up_queue_fraction=0.25, up_occupancy=0.95,
            up_pressure_rate=1e9,      # queue/occupancy drive this drill
            down_queue_fraction=0.1, down_occupancy=0.5,
            down_stable_s=0.3, cooldown_up_s=0.15, cooldown_down_s=0.3,
            max_step=2, assumed_warmup_s=0.05, warmup_cost_factor=1.0,
        ),
    ))
    registry = MetricsRegistry()
    router = FleetRouter(lambda reg: SlowEngine(), cfg, replicas=1,
                         registry=registry)
    assert router.wait_ready(timeout=10)
    scaler = Autoscaler(router, cfg.serve.autoscale)
    peak_seen = [1]
    stop_watch = threading.Event()

    def watch():  # bounds witness: live count sampled through the storm
        while not stop_watch.wait(0.01):
            peak_seen[0] = max(peak_seen[0], router.live_replica_count())

    watcher = threading.Thread(target=watch, daemon=True)
    watcher.start()

    counts = {"ok": 0, "shed": 0, "lost": 0}
    lock = threading.Lock()

    def client(cid, stop_at):
        i = 0
        while time.monotonic() < stop_at:
            prio = "interactive" if (cid + i) % 2 == 0 else "batch"
            try:
                router.submit(_req(cid * 100_000 + i, priority=prio)) \
                    .result(timeout=60)
                k = "ok"
            except Overloaded:
                k = "shed"
                time.sleep(0.002)
            except Exception:
                k = "lost"
            with lock:
                counts[k] += 1
            i += 1

    with CompileMonitor() as mon:
        # flash crowd: 12 closed-loop clients against 1 replica of ~50
        # req/s — the queue builds and the policy must grow the fleet
        stop_at = time.monotonic() + 2.0
        threads = [threading.Thread(target=client, args=(c, stop_at),
                                    daemon=True) for c in range(12)]
        for t in threads:
            t.start()
        grew = False
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if router.live_replica_count() > 1:
                grew = True
                break
            time.sleep(0.01)
        for t in threads:
            t.join()
        assert grew, "flash crowd never triggered a scale-up"
        # recovery: load gone — the fleet must shrink back to the floor
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if router.live_replica_count() == 1:
                break
            time.sleep(0.02)
        shrank = router.live_replica_count() == 1
    stop_watch.set()
    watcher.join(timeout=5)
    scaler.close()
    router.close()
    assert shrank, "fleet never shrank back after the storm drained"
    assert counts["ok"] > 0
    assert counts["lost"] == 0, f"lost requests in the storm: {counts}"
    assert peak_seen[0] <= 3, "autoscaler exceeded max_replicas"
    assert mon.count == 0    # the policy layer must never compile
    assert registry.value("serve_autoscale_target") == 1
    snap = registry.snapshot()["counters"]
    ups = sum(v for k, v in snap.items()
              if k.startswith("serve_autoscale_decisions_total")
              and 'reason="calm"' not in k)
    downs = snap.get('serve_autoscale_decisions_total{reason="calm"}', 0)
    assert ups >= 1 and downs >= 1
