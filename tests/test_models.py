"""Model tests: shapes, jit-traceability, FiLM topology, masking invariance."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from speakingstyle_tpu.configs.config import (
    Config,
    ModelConfig,
    ReferenceEncoderConfig,
    TransformerConfig,
    VariancePredictorConfig,
)
from speakingstyle_tpu.models.fastspeech2 import FastSpeech2
from speakingstyle_tpu.models.loss import fastspeech2_loss, film_gate_l2


def tiny_config(**model_overrides):
    tf = TransformerConfig(
        encoder_layer=2, decoder_layer=2, encoder_hidden=16, decoder_hidden=16,
        encoder_head=2, decoder_head=2, conv_filter_size=32,
    )
    ref = ReferenceEncoderConfig(
        encoder_layer=1, encoder_head=2, encoder_hidden=16,
        conv_layer=1, conv_filter_size=32,
    )
    vp = VariancePredictorConfig(filter_size=16)
    mc = ModelConfig(
        transformer=tf, reference_encoder=ref, variance_predictor=vp,
        max_seq_len=64, compute_dtype="float32", **model_overrides,
    )
    return Config(model=mc)


def make_batch(B=2, L=6, T=18, n_mels=80, seed=0):
    rng = np.random.RandomState(seed)
    texts = jnp.asarray(rng.randint(1, 300, (B, L)))
    src_lens = jnp.asarray([L, L - 2])
    d = np.full((B, L), 3)
    d[1, L - 2:] = 0
    d = jnp.asarray(d)
    mel_lens = d.sum(1)
    mels = jnp.asarray(rng.randn(B, T, n_mels).astype(np.float32))
    p = jnp.asarray(rng.randn(B, L).astype(np.float32))
    e = jnp.asarray(rng.randn(B, L).astype(np.float32))
    return texts, src_lens, mels, mel_lens, p, e, d


@pytest.fixture(scope="module")
def model_and_vars():
    cfg = tiny_config()
    model = FastSpeech2(config=cfg, pitch_stats=(-2, 8), energy_stats=(-1, 9))
    texts, src_lens, mels, mel_lens, p, e, d = make_batch()
    rng = jax.random.PRNGKey(0)
    variables = model.init(
        {"params": rng, "dropout": rng},
        jnp.zeros((2,), jnp.int32), texts, src_lens,
        mels=mels, mel_lens=mel_lens, max_mel_len=18,
        p_targets=p, e_targets=e, d_targets=d,
    )
    return model, variables


def test_teacher_forced_shapes(model_and_vars):
    model, variables = model_and_vars
    texts, src_lens, mels, mel_lens, p, e, d = make_batch()
    out = model.apply(
        variables, jnp.zeros((2,), jnp.int32), texts, src_lens,
        mels=mels, mel_lens=mel_lens, max_mel_len=18,
        p_targets=p, e_targets=e, d_targets=d,
    )
    assert out["mel"].shape == (2, 18, 80)
    assert out["mel_postnet"].shape == (2, 18, 80)
    assert out["log_duration_prediction"].shape == (2, 6)
    assert out["mel_lens"].tolist() == [18, 12]


def test_free_running_uses_predicted_durations(model_and_vars):
    model, variables = model_and_vars
    texts, src_lens, mels, mel_lens, *_ = make_batch()
    out = model.apply(
        variables, jnp.zeros((2,), jnp.int32), texts, src_lens,
        mels=mels, mel_lens=mel_lens, max_mel_len=30,
    )
    assert out["mel_postnet"].shape == (2, 30, 80)
    assert out["durations"].dtype == jnp.int32


def test_film_gate_count(model_and_vars):
    # FiLM sites: encoder blocks + decoder blocks + duration predictor ONLY
    # (reference: model/modules.py:121-131 — pitch/energy unconditioned)
    _, variables = model_and_vars
    n_sites = 2 + 2 + 1
    assert float(film_gate_l2(variables["params"])) == pytest.approx(2 * n_sites)


def test_padding_invariance(model_and_vars):
    """Content beyond src_len must not affect real outputs."""
    model, variables = model_and_vars
    texts, src_lens, mels, mel_lens, p, e, d = make_batch()
    texts2 = texts.at[1, 4:].set(7)  # item 1 has src_len 4; perturb its padding
    out1 = model.apply(
        variables, jnp.zeros((2,), jnp.int32), texts, src_lens,
        mels=mels, mel_lens=mel_lens, max_mel_len=18,
        p_targets=p, e_targets=e, d_targets=d,
    )
    out2 = model.apply(
        variables, jnp.zeros((2,), jnp.int32), texts2, src_lens,
        mels=mels, mel_lens=mel_lens, max_mel_len=18,
        p_targets=p, e_targets=e, d_targets=d,
    )
    np.testing.assert_allclose(
        np.asarray(out1["mel"][1, :12]), np.asarray(out2["mel"][1, :12]),
        rtol=0, atol=1e-5,
    )


@pytest.mark.slow
def test_jit_and_grad(model_and_vars):
    model, variables = model_and_vars
    texts, src_lens, mels, mel_lens, p, e, d = make_batch()

    @jax.jit
    def loss_fn(params):
        out = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            jnp.zeros((2,), jnp.int32), texts, src_lens,
            mels=mels, mel_lens=mel_lens, max_mel_len=18,
            p_targets=p, e_targets=e, d_targets=d,
        )
        return fastspeech2_loss(out, mels, p, e, d, params, lambda_f=0.001)["total_loss"]

    g = jax.grad(loss_fn)(variables["params"])
    norms = [float(jnp.abs(x).sum()) for x in jax.tree_util.tree_leaves(g)]
    assert all(np.isfinite(n) for n in norms)
    assert sum(n > 0 for n in norms) > len(norms) * 0.5


def test_multi_speaker_embedding():
    cfg = tiny_config(multi_speaker=True)
    model = FastSpeech2(config=cfg, pitch_stats=(-2, 8), energy_stats=(-1, 9), n_speakers=4)
    texts, src_lens, mels, mel_lens, p, e, d = make_batch()
    rng = jax.random.PRNGKey(0)
    variables = model.init(
        {"params": rng, "dropout": rng},
        jnp.asarray([0, 3]), texts, src_lens,
        mels=mels, mel_lens=mel_lens, max_mel_len=18,
        p_targets=p, e_targets=e, d_targets=d,
    )
    assert "speaker_emb" in variables["params"]
    out_a = model.apply(
        variables, jnp.asarray([0, 3]), texts, src_lens,
        mels=mels, mel_lens=mel_lens, max_mel_len=18,
        p_targets=p, e_targets=e, d_targets=d,
    )
    out_b = model.apply(
        variables, jnp.asarray([1, 3]), texts, src_lens,
        mels=mels, mel_lens=mel_lens, max_mel_len=18,
        p_targets=p, e_targets=e, d_targets=d,
    )
    assert not np.allclose(out_a["mel"][0], out_b["mel"][0])
    np.testing.assert_allclose(out_a["mel"][1], out_b["mel"][1], atol=1e-6)


@pytest.mark.slow
def test_remat_stack_runs():
    # regression: nn.remat static_argnums must point at `deterministic`
    import dataclasses
    from speakingstyle_tpu.configs.config import ShardingConfig, TrainConfig

    cfg = tiny_config()
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, sharding=ShardingConfig(remat=True))
    )
    model = FastSpeech2(config=cfg, pitch_stats=(-2, 8), energy_stats=(-1, 9))
    texts, src_lens, mels, mel_lens, p, e, d = make_batch()
    rng = jax.random.PRNGKey(0)
    variables = model.init(
        {"params": rng, "dropout": rng},
        jnp.zeros((2,), jnp.int32), texts, src_lens,
        mels=mels, mel_lens=mel_lens, max_mel_len=18,
        p_targets=p, e_targets=e, d_targets=d, deterministic=False,
    )
    assert variables["params"]


def test_loss_ignores_padded_frames(model_and_vars):
    model, variables = model_and_vars
    texts, src_lens, mels, mel_lens, p, e, d = make_batch()
    out = model.apply(
        variables, jnp.zeros((2,), jnp.int32), texts, src_lens,
        mels=mels, mel_lens=mel_lens, max_mel_len=18,
        p_targets=p, e_targets=e, d_targets=d,
    )
    l1 = fastspeech2_loss(out, mels, p, e, d, variables["params"])
    mels_perturbed = mels.at[1, 12:].add(100.0)  # item 1 true mel_len is 12
    l2 = fastspeech2_loss(out, mels_perturbed, p, e, d, variables["params"])
    assert float(l1["mel_loss"]) == pytest.approx(float(l2["mel_loss"]))


@pytest.mark.slow
def test_conv_impls_identical_tree_and_outputs(model_and_vars):
    """conv_impl xla/unfold/pallas: same param tree, same forward numbers
    on the SAME params — checkpoints are impl-portable (ops/conv.py)."""
    model, variables = model_and_vars
    texts, src_lens, mels, mel_lens, p, e, d = make_batch()
    kwargs = dict(
        mels=mels, mel_lens=mel_lens, max_mel_len=18,
        p_targets=p, e_targets=e, d_targets=d, deterministic=True,
    )
    speakers = jnp.zeros((2,), jnp.int32)

    base_cfg = tiny_config()  # conv_impl="xla" (ModelConfig default)
    outs = {}
    trees = {}
    for impl in ("xla", "unfold", "pallas"):
        cfg = dataclasses.replace(
            base_cfg, model=dataclasses.replace(base_cfg.model, conv_impl=impl)
        )
        m = FastSpeech2(
            config=cfg, pitch_stats=(-2, 8), energy_stats=(-1, 9)
        )
        init = m.init(
            {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(0)},
            speakers, texts, src_lens, **kwargs,
        )
        trees[impl] = jax.tree_util.tree_structure(init["params"])
        outs[impl] = m.apply(variables, speakers, texts, src_lens, **kwargs)

    assert trees["xla"] == trees["unfold"] == trees["pallas"]
    for impl in ("unfold", "pallas"):
        np.testing.assert_allclose(
            np.asarray(outs[impl]["mel_postnet"]),
            np.asarray(outs["xla"]["mel_postnet"]),
            atol=2e-4,
            err_msg=impl,
        )


def test_attention_softmax_dtype_bf16_close():
    """attention_softmax_dtype="bfloat16" is an A/B knob: outputs stay
    close to the f32-softmax reference path (same params)."""
    cfg32 = tiny_config()
    cfgbf = dataclasses.replace(
        cfg32,
        model=dataclasses.replace(
            cfg32.model, attention_softmax_dtype="bfloat16"
        ),
    )
    texts, src_lens, mels, mel_lens, p, e, d = make_batch()
    speakers = jnp.zeros((2,), jnp.int32)
    kwargs = dict(
        mels=mels, mel_lens=mel_lens, max_mel_len=18,
        p_targets=p, e_targets=e, d_targets=d, deterministic=True,
    )
    m32 = FastSpeech2(config=cfg32, pitch_stats=(-2, 8), energy_stats=(-1, 9))
    mbf = FastSpeech2(config=cfgbf, pitch_stats=(-2, 8), energy_stats=(-1, 9))
    variables = m32.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(0)},
        speakers, texts, src_lens, **kwargs,
    )
    out32 = m32.apply(variables, speakers, texts, src_lens, **kwargs)
    outbf = mbf.apply(variables, speakers, texts, src_lens, **kwargs)
    np.testing.assert_allclose(
        np.asarray(outbf["mel_postnet"]),
        np.asarray(out32["mel_postnet"]),
        atol=0.15,  # bf16 softmax rounding through 2+2 blocks
    )
