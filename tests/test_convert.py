"""FastSpeech2 checkpoint-converter structural parity.

Builds a synthetic state_dict with the REFERENCE's exact key names/shapes
(reference: model/fastspeech2.py, model/modules.py, transformer/ — grep'd
module attribute structure) and asserts convert_fastspeech2 produces a tree
that matches our model.init exactly (same paths, same shapes).
"""

import jax
import numpy as np
import pytest

from speakingstyle_tpu.compat.torch_convert import convert_fastspeech2
from speakingstyle_tpu.configs.config import Config
from speakingstyle_tpu.models.factory import build_model, init_variables

H = 256        # transformer hidden
FFN = 1024     # conv_filter_size
VP = 256       # variance predictor filter
REF_F = 1024   # reference-encoder conv filter
BINS = 256
MELS = 80
VOCAB = 361


def _rand(shape):
    return np.random.default_rng(abs(hash(shape)) % 2**32).standard_normal(
        shape
    ).astype(np.float32)


def _add_dense(sd, prefix, d_in, d_out, bias=True):
    sd[prefix + ".weight"] = _rand((d_out, d_in))
    if bias:
        sd[prefix + ".bias"] = _rand((d_out,))


def _add_conv1d(sd, prefix, c_in, c_out, k):
    sd[prefix + ".weight"] = _rand((c_out, c_in, k))
    sd[prefix + ".bias"] = _rand((c_out,))


def _add_ln(sd, prefix, d):
    sd[prefix + ".weight"] = _rand((d,))
    sd[prefix + ".bias"] = _rand((d,))


def _add_fft_block(sd, prefix, d_model, d_inner, kernels, film):
    for name in ("w_qs", "w_ks", "w_vs", "fc"):
        _add_dense(sd, f"{prefix}.slf_attn.{name}", d_model, d_model)
    _add_ln(sd, f"{prefix}.slf_attn.layer_norm", d_model)
    _add_conv1d(sd, f"{prefix}.pos_ffn.w_1", d_model, d_inner, kernels[0])
    _add_conv1d(sd, f"{prefix}.pos_ffn.w_2", d_inner, d_model, kernels[1])
    _add_ln(sd, f"{prefix}.pos_ffn.layer_norm", d_model)
    if film:
        sd[f"{prefix}.film.s_gamma"] = _rand((1,))
        sd[f"{prefix}.film.s_beta"] = _rand((1,))


def _add_variance_predictor(sd, prefix):
    # torch always creates the film params even where forward never uses them
    _add_conv1d(sd, f"{prefix}.conv_layer.conv1d_1.conv", H, VP, 3)
    _add_ln(sd, f"{prefix}.conv_layer.layer_norm_1", VP)
    _add_conv1d(sd, f"{prefix}.conv_layer.conv1d_2.conv", VP, VP, 3)
    _add_ln(sd, f"{prefix}.conv_layer.layer_norm_2", VP)
    sd[f"{prefix}.film.s_gamma"] = _rand((1,))
    sd[f"{prefix}.film.s_beta"] = _rand((1,))
    _add_dense(sd, f"{prefix}.linear_layer", VP, 1)


def make_reference_state_dict() -> dict:
    sd = {}
    sd["encoder.src_word_emb.weight"] = _rand((VOCAB, H))
    sd["encoder.position_enc"] = _rand((1, 1001, H))  # skipped buffer
    for i in range(4):
        _add_fft_block(sd, f"encoder.layer_stack.{i}", H, FFN, (9, 1), film=True)
    sd["decoder.position_enc"] = _rand((1, 1001, H))
    for i in range(6):
        _add_fft_block(sd, f"decoder.layer_stack.{i}", H, FFN, (9, 1), film=True)

    for name in ("duration_predictor", "pitch_predictor", "energy_predictor"):
        _add_variance_predictor(sd, f"variance_adaptor.{name}")
    sd["variance_adaptor.pitch_bins"] = _rand((BINS - 1,))   # skipped buffer
    sd["variance_adaptor.energy_bins"] = _rand((BINS - 1,))  # skipped buffer
    sd["variance_adaptor.pitch_embedding.weight"] = _rand((BINS, H))
    sd["variance_adaptor.energy_embedding.weight"] = _rand((BINS, H))

    for i in range(3):
        _add_conv1d(
            sd,
            f"reference_encoder.layer_stack.{i}.0.conv",
            MELS if i == 0 else REF_F,
            REF_F,
            3,
        )
        _add_ln(sd, f"reference_encoder.layer_stack.{i}.2", REF_F)
    sd["reference_encoder.position_enc"] = _rand((1, 1001, REF_F))
    _add_dense(sd, "reference_encoder.fftb_linear.linear", REF_F, H, bias=False)
    for i in range(4):
        _add_fft_block(
            sd, f"reference_encoder.fftb_stack.{i}", H, REF_F, (3, 3), film=False
        )
    _add_dense(
        sd, "reference_encoder.feature_wise_affine.linear", H, 2 * H, bias=False
    )

    sd["mel_linear.weight"] = _rand((MELS, H))
    sd["mel_linear.bias"] = _rand((MELS,))

    for i in range(5):
        c_in = MELS if i == 0 else 512
        c_out = MELS if i == 4 else 512
        _add_conv1d(sd, f"postnet.convolutions.{i}.0.conv", c_in, c_out, 5)
        _add_ln(sd, f"postnet.convolutions.{i}.1", c_out)
        sd[f"postnet.convolutions.{i}.1.running_mean"] = _rand((c_out,))
        sd[f"postnet.convolutions.{i}.1.running_var"] = np.abs(_rand((c_out,)))
        sd[f"postnet.convolutions.{i}.1.num_batches_tracked"] = np.zeros((), np.int64)
    return sd


def _tree_shapes(tree):
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return {
        jax.tree_util.keystr(path): tuple(leaf.shape) for path, leaf in flat
    }


@pytest.mark.parametrize("dp_prefix", [False, True])
@pytest.mark.slow
def test_convert_fastspeech2_matches_init_tree(dp_prefix):
    sd = make_reference_state_dict()
    if dp_prefix:  # nn.DataParallel checkpoints (reference: train.py:45)
        sd = {"module." + k: v for k, v in sd.items()}
    converted = convert_fastspeech2(sd)

    cfg = Config()
    model = build_model(cfg)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))

    got_p = _tree_shapes(converted["params"])
    want_p = _tree_shapes(variables["params"])
    assert got_p == want_p, (
        f"missing: {sorted(set(want_p) - set(got_p))[:8]}; "
        f"extra: {sorted(set(got_p) - set(want_p))[:8]}; "
        f"shape diffs: {[(k, got_p[k], want_p[k]) for k in got_p if k in want_p and got_p[k] != want_p[k]][:8]}"
    )
    got_b = _tree_shapes(converted["batch_stats"])
    want_b = _tree_shapes(variables["batch_stats"])
    assert got_b == want_b


def test_converted_params_run_forward():
    import jax.numpy as jnp

    sd = make_reference_state_dict()
    converted = convert_fastspeech2(sd)
    cfg = Config()
    model = build_model(cfg)
    B, L, T = 2, 6, 12
    out = model.apply(
        {
            "params": converted["params"],
            "batch_stats": converted["batch_stats"],
        },
        speakers=jnp.zeros((B,), jnp.int32),
        texts=jnp.ones((B, L), jnp.int32),
        src_lens=jnp.full((B,), L, jnp.int32),
        mels=jnp.zeros((B, T, MELS), jnp.float32),
        mel_lens=jnp.full((B,), T, jnp.int32),
        max_mel_len=T,
        p_targets=jnp.zeros((B, L), jnp.float32),
        e_targets=jnp.zeros((B, L), jnp.float32),
        d_targets=jnp.full((B, L), 2, jnp.int32),
        deterministic=True,
    )
    assert out["mel_postnet"].shape == (B, T, MELS)
    assert np.isfinite(np.asarray(out["mel_postnet"])).all()


@pytest.mark.slow
def test_convert_cli_roundtrip(tmp_path, synthetic_preprocessed):
    """``python -m speakingstyle_tpu convert``: torch ckpt -> Orbax dir at
    the filename's step, restorable, with the --eval_mel_l1 gate running a
    real val pass (the runner VERDICT asks to have ready for the released
    900k checkpoint)."""
    torch = pytest.importorskip("torch")
    import yaml

    from speakingstyle_tpu.__main__ import main as cli_main
    from speakingstyle_tpu.training.checkpoint import CheckpointManager
    from speakingstyle_tpu.training.optim import make_optimizer
    from speakingstyle_tpu.training.state import TrainState

    np_sd = make_reference_state_dict()
    sd = {k: torch.from_numpy(np.asarray(v)) for k, v in np_sd.items()}
    ckpt_file = tmp_path / "900000.pth.tar"
    torch.save({"model": sd, "optimizer": {}}, str(ckpt_file))

    docs = {
        "preprocess": {"path": {"preprocessed_path": synthetic_preprocessed}},
        "model": {},
        "train": {"path": {"ckpt_path": str(tmp_path / "ckpt"),
                           "log_path": str(tmp_path / "log"),
                           "result_path": str(tmp_path / "result")}},
    }
    paths = {}
    for name, doc in docs.items():
        p = tmp_path / f"{name}.yaml"
        p.write_text(yaml.safe_dump(doc))
        paths[name] = str(p)

    cli_main(["convert", "-p", paths["preprocess"], "-m", paths["model"],
              "-t", paths["train"], "--ckpt", str(ckpt_file),
              "--eval_mel_l1"])

    cfg = Config()
    model = build_model(cfg)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    state = TrainState.create(variables, make_optimizer(cfg.train))
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    assert mgr.latest_step() == 900000
    restored = mgr.restore(state)
    np.testing.assert_allclose(
        np.asarray(restored.params["mel_linear"]["kernel"]),
        np_sd["mel_linear.weight"].T,
        rtol=1e-6,
    )
    mgr.close()
