"""Latency-pipeline acceptance tests (tier-1, PR 11).

Four claims, mirroring ARCHITECTURE.md "Latency pipeline":
  1. correctness — the double-buffered stream (``stream_depth >= 2``)
     emits wav bit-identical to the sequential path at any depth,
     including the edge windows (single-window utterances, tails shorter
     than the overlap, exact window multiples);
  2. zero steady-state compiles with the pipeline on, measured on the
     backend's own monitoring bus;
  3. allocation-free, leak-free staging — ``BufferPool`` leases return
     on every path: normal collect, abandoned streams, and a dispatch
     stolen by the hang watchdog mid-flight (the PR 9 chaos path), with
     the alloc counter flat across post-warmup traffic;
  4. the frontend pool preserves PR 9 semantics — the SLO clock starts
     at admission, so a deadline expiry still resolves 504 pre-dispatch
     without ever waiting on the frontend.

Plus unit coverage for the two new primitives (``FrontendPool``,
``BufferPool``) themselves.
"""

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from speakingstyle_tpu.configs.config import (
    Config,
    FleetConfig,
    ModelConfig,
    ReferenceEncoderConfig,
    ServeConfig,
    StyleConfig,
    TransformerConfig,
    VarianceEmbeddingConfig,
    VariancePredictorConfig,
)
from speakingstyle_tpu.faults import FaultPlan
from speakingstyle_tpu.obs import MetricsRegistry
from speakingstyle_tpu.serving import streaming
from speakingstyle_tpu.serving.batcher import ShutdownError
from speakingstyle_tpu.serving.engine import CompileMonitor, SynthesisRequest
from speakingstyle_tpu.serving.fleet import FleetRouter
from speakingstyle_tpu.serving.frontend import FrontendPool, PendingRequest
from speakingstyle_tpu.serving.pool import BufferPool
from speakingstyle_tpu.serving.resilience import DeadlineExceeded

# ---------------------------------------------------------------------------
# shared tiny model (test_serving.py's recipe + a small stream window so
# one utterance spans several windows, incl. a short tail)
# ---------------------------------------------------------------------------


def _tiny_cfg(**fleet_kw):
    fleet = dict(
        stream_window=8, rewarm_backoff_s=0.05, rewarm_backoff_max_s=1.0,
        class_deadline_ms={"interactive": 120_000.0, "batch": 240_000.0},
    )
    fleet.update(fleet_kw)
    return Config(
        model=ModelConfig(
            transformer=TransformerConfig(
                encoder_layer=1, decoder_layer=1, encoder_hidden=16,
                decoder_hidden=16, conv_filter_size=16,
                conv_kernel_size=(3, 1),
            ),
            reference_encoder=ReferenceEncoderConfig(
                encoder_layer=1, encoder_head=2, encoder_hidden=16,
                conv_layer=1, conv_filter_size=16,
            ),
            variance_predictor=VariancePredictorConfig(filter_size=16),
            variance_embedding=VarianceEmbeddingConfig(n_bins=8),
            postnet_embedding_dim=16, postnet_layers=2,
            max_seq_len=48, compute_dtype="float32",
        ),
        serve=ServeConfig(
            batch_buckets=[1, 2], src_buckets=[16], mel_buckets=[32],
            frames_per_phoneme=2, max_wait_ms=20.0,
            style=StyleConfig(ref_buckets=[32]),
            fleet=FleetConfig(**fleet),
        ),
    )


@pytest.fixture(scope="module")
def tiny_parts():
    import jax

    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.models.hifigan import Generator

    cfg = _tiny_cfg()
    model = build_model(cfg, n_position=49)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    # bias the duration predictor so random weights predict ~2 frames
    # per phoneme — real multi-window streams flow end-to-end
    bias = variables["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"]
    variables["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"] = bias + 1.1
    gen = Generator(
        upsample_rates=(2, 2), upsample_kernel_sizes=(4, 4),
        upsample_initial_channel=16, resblock_kernel_sizes=(3,),
        resblock_dilation_sizes=((1,),),
    )
    gparams = gen.init(
        jax.random.PRNGKey(0), np.zeros((1, 8, 80), np.float32)
    )["params"]
    return cfg, model, variables, gen, gparams


@pytest.fixture(scope="module")
def pipe_engine(tiny_parts):
    from speakingstyle_tpu.serving.engine import SynthesisEngine

    cfg, model, variables, gen, gparams = tiny_parts
    engine = SynthesisEngine(cfg, variables, vocoder=(gen, gparams),
                             model=model)
    engine.precompile()
    return engine


def _mkreq(i, L=10, T=20, **kw):
    rng = np.random.default_rng(i)
    kw.setdefault(
        "ref_mel", rng.standard_normal((T, 80)).astype(np.float32)
    )
    return SynthesisRequest(
        id=f"utt{i}",
        sequence=rng.integers(1, 300, L).astype(np.int32),
        **kw,
    )


def _stream_params(engine):
    window = engine.cfg.serve.fleet.stream_window
    overlap = streaming.resolve_overlap(
        engine.cfg.serve.fleet.stream_overlap, engine.vocoder[0]
    )
    return window, overlap


# ---------------------------------------------------------------------------
# 1. bit-exactness: pipelined vs sequential, incl. edge windows
# ---------------------------------------------------------------------------


def test_stream_pipelined_bit_exact_vs_sequential(pipe_engine):
    """The pipeline reorders *waiting*, never the per-window math: at
    any depth the concatenated chunks equal the sequential (depth=1)
    stream bit-for-bit, and cover exactly mel_len * hop samples."""
    engine = pipe_engine
    window, overlap = _stream_params(engine)
    hop = int(engine.vocoder[0].hop_factor)
    res = engine.run([_mkreq(1, L=16, stream=True)])[0]
    assert res.mel_len > 2 * window, "fixture must span several windows"
    seq = np.concatenate(list(
        streaming.stream_wav(engine, res, window, overlap, depth=1)
    ))
    assert seq.shape == (res.mel_len * hop,) and seq.dtype == np.int16
    for depth in (2, 3, 4):
        piped = np.concatenate(list(
            streaming.stream_wav(engine, res, window, overlap, depth=depth)
        ))
        np.testing.assert_array_equal(piped, seq)


def test_stream_pipelined_bit_exact_edge_windows(pipe_engine):
    """Edge geometries where the overlap-tail logic can go wrong: a
    single short window, a tail shorter than the overlap, an exact
    window multiple, and window+1 (1-frame tail). stream_wav reads only
    (mel, mel_len), so slicing a real mel drives each case exactly."""
    engine = pipe_engine
    window, overlap = _stream_params(engine)
    hop = int(engine.vocoder[0].hop_factor)
    res = engine.run([_mkreq(2, L=16, stream=True)])[0]
    lengths = sorted({
        1, window - 1, window, window + 1, 2 * window, int(res.mel_len),
    })
    assert lengths[-1] <= res.mel_len
    for m in lengths:
        clip = SimpleNamespace(mel=res.mel[:m], mel_len=m)
        seq = np.concatenate(list(
            streaming.stream_wav(engine, clip, window, overlap, depth=1)
        ))
        piped = np.concatenate(list(
            streaming.stream_wav(engine, clip, window, overlap, depth=3)
        ))
        assert seq.shape == (m * hop,)
        np.testing.assert_array_equal(piped, seq)


def test_stream_depth_validated(pipe_engine):
    res = SimpleNamespace(mel=np.zeros((4, 80), np.float32), mel_len=4)
    with pytest.raises(ValueError, match="depth"):
        list(streaming.stream_wav(pipe_engine, res, 8, 2, depth=0))


# ---------------------------------------------------------------------------
# 2. zero steady-state compiles with the pipeline on
# ---------------------------------------------------------------------------


def test_stream_pipeline_zero_steady_state_compiles(pipe_engine):
    """After one warmup pass the pipelined stream performs ZERO XLA
    compiles — same invariant the batch path proves, measured on the
    backend's monitoring bus."""
    engine = pipe_engine
    window, overlap = _stream_params(engine)
    res = engine.run([_mkreq(3, L=16, stream=True)])[0]
    list(streaming.stream_wav(engine, res, window, overlap, depth=2))
    before = engine.compile_count
    with CompileMonitor() as mon:
        for depth in (1, 2, 3):
            chunks = list(
                streaming.stream_wav(engine, res, window, overlap,
                                     depth=depth)
            )
            assert chunks
    assert mon.count == 0, "the stream pipeline compiled after warmup"
    assert engine.compile_count == before


# ---------------------------------------------------------------------------
# 3. pool: abandoned streams and the hang-watchdog steal leak nothing
# ---------------------------------------------------------------------------


def test_abandoned_stream_returns_pooled_buffers(pipe_engine):
    """A consumer that walks away mid-stream (client disconnect) leaves
    zero leased buffers behind — the generator's finally abandons every
    in-flight handle — and later streams stay allocation-free."""
    engine = pipe_engine
    window, overlap = _stream_params(engine)
    res = engine.run([_mkreq(4, L=16, stream=True)])[0]
    list(streaming.stream_wav(engine, res, window, overlap, depth=3))
    assert engine.pool.outstanding == 0
    allocated = engine.pool.allocated
    it = streaming.stream_wav(engine, res, window, overlap, depth=3)
    next(it)                       # pipeline primed: handles in flight
    it.close()                     # consumer gone
    assert engine.pool.outstanding == 0
    chunks = list(streaming.stream_wav(engine, res, window, overlap))
    assert sum(len(c) for c in chunks) == res.mel_len * 4
    assert engine.pool.allocated == allocated, "steady state allocated"
    assert engine.pool.outstanding == 0


class _Events:
    """In-memory stand-in for the JSONL event bus (test_chaos.py's)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.records = []

    def emit(self, event, **fields):
        with self.lock:
            self.records.append((event, fields))

    def kinds(self):
        with self.lock:
            return [k for k, _ in self.records]

    def of(self, kind):
        with self.lock:
            return [dict(f) for k, f in self.records if k == kind]


def test_pool_no_leak_under_replica_hang_steal(tiny_parts):
    """The PR 9 chaos path against the real engine: a dispatch stuck
    past the hang watchdog is stolen and retried on the re-warmed
    replica; when the hung worker finishes anyway, its results are
    discarded (no duplicate audio) and every pooled staging buffer it
    leased is back — outstanding 0 on both engines, allocs flat across
    post-steal traffic."""
    from speakingstyle_tpu.serving.engine import SynthesisEngine

    cfg, model, variables, gen, gparams = tiny_parts
    cfg = _tiny_cfg(hang_watchdog_s=0.3)
    engines = []
    plan = FaultPlan()
    events = _Events()
    reg = MetricsRegistry()

    def factory(registry):
        eng = SynthesisEngine(cfg, variables, vocoder=(gen, gparams),
                              model=model, registry=registry)
        engines.append(eng)
        return eng

    with FleetRouter(factory, cfg, replicas=1, registry=reg,
                     events=events, fault_plan=plan) as router:
        assert router.wait_ready(timeout=300)
        for b in engines[0].lattice.batch_buckets:
            engines[0].run([_mkreq(700 + b * 10 + j) for j in range(b)])
        for f in [router.submit(_mkreq(i)) for i in range(2)]:
            assert f.result(timeout=120).wav is not None
        # the NEXT dispatch hangs past the watchdog, gets stolen, and
        # retries on the re-warmed (second) engine
        plan.arm("replica_hang", router.dispatch_total + 1)
        res = router.submit(_mkreq(10)).result(timeout=300)
        assert res.id == "utt10" and res.wav is not None
        assert len(engines) == 2
        rf = events.of("replica_failure")
        assert len(rf) == 1 and rf[0]["kind"] == "hang"
        # the hung worker wakes, finishes its dispatch on engine #1,
        # finds its claim stolen, and discards — releasing its leases
        deadline = time.monotonic() + 30
        while (time.monotonic() < deadline
               and "dispatch_discarded" not in events.kinds()):
            time.sleep(0.01)
        assert "dispatch_discarded" in events.kinds()
        # post-steal steady state: allocation-free and leak-free
        for f in [router.submit(_mkreq(20 + i)) for i in range(2)]:
            assert f.result(timeout=120).wav is not None
        allocated = [e.pool.allocated for e in engines]
        for f in [router.submit(_mkreq(30 + i)) for i in range(3)]:
            assert f.result(timeout=120).wav is not None
        for i, eng in enumerate(engines):
            assert eng.pool.outstanding == 0, f"engine {i} leaked a lease"
            assert eng.pool.allocated == allocated[i]


# ---------------------------------------------------------------------------
# 4. frontend pool preserves the deadline contract
# ---------------------------------------------------------------------------


class _GatedFrontend:
    """Frontend whose G2P blocks until released — models a slow/wedged
    frontend so the test can prove the 504 never waited on it."""

    def __init__(self):
        self.gate = threading.Event()
        self.calls = 0

    def request(self, req_id, payload):
        self.calls += 1
        self.gate.wait(timeout=30)
        return SimpleNamespace(id=req_id, stream=False, arrival=None)


def test_frontend_pool_deadline_still_504s_pre_dispatch():
    """The SLO clock starts at the handler's admission stamp, not at
    G2P completion: with the only replica still warming and the
    frontend wedged, the EDF sweep resolves DeadlineExceeded on budget
    — the pending handle is never waited on (still unresolved)."""
    warm_gate = threading.Event()

    def factory(reg):
        warm_gate.wait(timeout=30)
        return SimpleNamespace(precompile=lambda: 0.0,
                               run=lambda requests: [])

    cfg = _tiny_cfg(class_deadline_ms={"interactive": 60.0,
                                       "batch": 2000.0})
    reg = MetricsRegistry()
    frontend = _GatedFrontend()
    pool = FrontendPool(frontend, workers=1, registry=reg)
    router = FleetRouter(factory, cfg, replicas=1, registry=reg)
    try:
        t0 = time.monotonic()
        pending = pool.prepare("r0", {"text": "too late"})
        fut = router.submit(pending)
        pool.dispatch(pending)
        exc = fut.exception(timeout=10)
        assert isinstance(exc, DeadlineExceeded)
        assert exc.klass == "interactive" and exc.budget_ms == 60.0
        # resolved by the budget sweep, and strictly pre-dispatch: the
        # frontend never finished, so nothing ever waited on it
        assert time.monotonic() - t0 < 5.0
        assert not pending._future.done()
        assert reg.value("serve_deadline_exceeded_total",
                         {"class": "interactive"}) == 1
    finally:
        warm_gate.set()
        frontend.gate.set()
        pool.close()
        router.close()


# ---------------------------------------------------------------------------
# FrontendPool unit coverage
# ---------------------------------------------------------------------------


class _EchoFrontend:
    def __init__(self, fail_ids=()):
        self.fail_ids = set(fail_ids)

    def request(self, req_id, payload):
        if req_id in self.fail_ids:
            raise ValueError(f"bad text for {req_id}")
        return SimpleNamespace(id=req_id, text=payload.get("text"),
                               stream=False, arrival=None)


def test_frontend_pool_resolves_and_restamps():
    """The resolved request carries the handler's admission stamp and
    stream flag (deadline math identical to inline mode), and the
    frontend cost lands in serve_frontend_seconds."""
    reg = MetricsRegistry()
    with FrontendPool(_EchoFrontend(), workers=2, registry=reg) as pool:
        pending = pool.prepare("q1", {"text": "hello"}, stream=True)
        pool.dispatch(pending)
        req = pending.resolve(timeout=10)
        assert req.id == "q1" and req.text == "hello"
        assert req.stream is True
        assert req.arrival == pending.arrival
        assert pending.resolve(timeout=0) is req      # idempotent
    snap = reg.snapshot()
    assert snap["histograms"]["serve_frontend_seconds"]["count"] == 1


def test_frontend_pool_error_resolves_exceptionally():
    reg = MetricsRegistry()
    with FrontendPool(_EchoFrontend(fail_ids={"bad"}), workers=1,
                      registry=reg) as pool:
        ok, bad = pool.prepare("ok", {}), pool.prepare("bad", {})
        pool.dispatch(bad)
        pool.dispatch(ok)
        with pytest.raises(ValueError, match="bad text"):
            bad.resolve(timeout=10)
        assert ok.resolve(timeout=10).id == "ok"      # worker survived
        assert reg.value("serve_frontend_errors_total") == 1


def test_frontend_pool_close_flushes_then_refuses():
    """close() drains already-dispatched work (the prefetch discipline),
    then a post-close dispatch resolves ShutdownError — no handle is
    ever stranded."""
    pool = FrontendPool(_EchoFrontend(), workers=1)
    flushed = [pool.prepare(f"f{i}", {}) for i in range(3)]
    for p in flushed:
        pool.dispatch(p)
    pool.close()
    for p in flushed:
        assert p.resolve(timeout=10).id == p.id
    late = pool.prepare("late", {})
    pool.dispatch(late)
    with pytest.raises(ShutdownError):
        late.resolve(timeout=10)
    pool.close()                                      # idempotent


def test_pending_request_validates_priority_type():
    with pytest.raises(ValueError, match="priority"):
        PendingRequest("r0", {"priority": 3})
    assert PendingRequest("r1", {"priority": "batch"}).priority == "batch"
    assert PendingRequest("r2", {}).priority is None


def test_frontend_pool_requires_workers():
    with pytest.raises(ValueError, match="worker"):
        FrontendPool(_EchoFrontend(), workers=0)


# ---------------------------------------------------------------------------
# BufferPool unit coverage
# ---------------------------------------------------------------------------


def test_buffer_pool_lease_reuse_and_metrics():
    reg = MetricsRegistry()
    pool = BufferPool(registry=reg)
    a = pool.acquire((4, 2), np.float32, fill=0)
    assert a.shape == (4, 2) and not a.any()
    assert pool.allocated == 1 and pool.outstanding == 1
    assert reg.value("serve_pool_outstanding") == 1
    a[:] = 7.0                                        # dirty it
    pool.release(a)
    assert pool.outstanding == 0
    assert reg.value("serve_pool_outstanding") == 0
    b = pool.acquire((4, 2), np.float32, fill=0)
    assert b is a                                     # reused, not fresh
    assert not b.any(), "reused lease must be re-filled"
    assert pool.allocated == 1
    assert reg.value("serve_pool_reuses_total") == 1
    # a different (shape, dtype) is a different free-list
    c = pool.acquire((4, 2), np.int16, fill=1)
    assert c.dtype == np.int16 and (c == 1).all()
    assert pool.allocated == 2
    pool.release(b)
    pool.release(c)
    assert pool.outstanding == 0


def test_buffer_pool_double_release_is_loud():
    pool = BufferPool()
    buf = pool.acquire((3,), np.float32)
    pool.release(buf)
    with pytest.raises(ValueError, match="release"):
        pool.release(buf)
    with pytest.raises(ValueError, match="release"):
        pool.release(np.zeros((3,), np.float32))      # foreign array
    assert pool.outstanding == 0
