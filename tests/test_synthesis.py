"""G2P frontend, per-word control, synthesis utils, and CLI surface."""

import json
import os

import numpy as np
import pytest

from speakingstyle_tpu.configs.config import (
    Config,
    ModelConfig,
    PathConfig,
    PreprocessConfig,
    ReferenceEncoderConfig,
    TrainConfig,
    TrainPathConfig,
    TransformerConfig,
    VarianceEmbeddingConfig,
    VariancePredictorConfig,
)
from speakingstyle_tpu.control import (
    english_word_spans,
    expand_word_controls,
    pad_control,
    spans_to_sequence,
)
from speakingstyle_tpu.text.g2p import (
    english_to_phones,
    mandarin_to_phones,
    preprocess_text,
    read_lexicon,
)

LEXICON = {"hello": ["HH", "AH0", "L", "OW1"], "world": ["W", "ER1", "L", "D"]}


def tiny_config(**kw):
    return Config(
        model=ModelConfig(
            transformer=TransformerConfig(
                encoder_layer=1, decoder_layer=1, encoder_hidden=32,
                decoder_hidden=32, conv_filter_size=64,
            ),
            reference_encoder=ReferenceEncoderConfig(
                encoder_layer=1, encoder_hidden=32, conv_filter_size=64,
            ),
            variance_predictor=VariancePredictorConfig(filter_size=32),
            variance_embedding=VarianceEmbeddingConfig(n_bins=16),
            max_seq_len=96,
        ),
        **kw,
    )


# ---------------------------------------------------------------------------
# G2P frontend
# ---------------------------------------------------------------------------

def test_read_lexicon(tmp_path):
    p = tmp_path / "lex.txt"
    p.write_text("HELLO HH AH0 L OW1\nhello X X\nWORLD  W ER1 L D\n")
    lex = read_lexicon(str(p))
    assert lex["hello"] == ["HH", "AH0", "L", "OW1"]  # first entry wins
    assert lex["world"] == ["W", "ER1", "L", "D"]


def test_english_to_phones_lexicon_hits():
    s = english_to_phones("Hello world", LEXICON, g2p=None)
    assert s == "{HH AH0 L OW1 W ER1 L D}"


def test_english_to_phones_punct_and_oov():
    s = english_to_phones("hello, zzqj world!", LEXICON, g2p=None)
    # comma -> sp, OOV without g2p -> spn, trailing ! stripped
    assert s == "{HH AH0 L OW1 sp spn W ER1 L D}"


def test_mandarin_to_phones_lexicon():
    lex = {"ni3": ["n", "i3"], "hao3": ["h", "ao3"]}
    s = mandarin_to_phones("ni3 hao3 oov", lex)
    assert s == "{n i3 h ao3 sp}"


def test_preprocess_text_sequence(tmp_path):
    p = tmp_path / "lex.txt"
    p.write_text("HELLO HH AH0 L OW1\n")
    seq = preprocess_text("hello", "en", str(p), ["english_cleaners"])
    assert seq.dtype == np.int32 and len(seq) == 4


# ---------------------------------------------------------------------------
# Per-word fine-grained control
# ---------------------------------------------------------------------------

def test_english_word_spans_and_sequence():
    spans = english_word_spans("Hello world", LEXICON, g2p=None)
    assert [w for w, _ in spans] == ["Hello", "world"]
    assert [len(ps) for _, ps in spans] == [4, 4]
    seq = spans_to_sequence(spans, ["english_cleaners"])
    assert len(seq) == 8


def test_expand_word_controls_variants():
    spans = [("a", ["HH", "AH0"]), ("b", ["W"])]
    np.testing.assert_allclose(expand_word_controls(spans, 2.0), [2, 2, 2])
    np.testing.assert_allclose(expand_word_controls(spans, [1.0, 3.0]), [1, 1, 3])
    np.testing.assert_allclose(
        expand_word_controls(spans, {1: 2.5}), [1, 1, 2.5]
    )
    with pytest.raises(ValueError):
        expand_word_controls(spans, [1.0])


def test_expand_word_controls_stays_aligned_with_dropped_phones():
    """text_to_sequence silently drops out-of-inventory phones; the control
    array must apply the same filter or every later word's factor shifts."""
    spans = [("a", ["HH", "ZZZNOTAPHONE"]), ("b", ["W"])]
    seq = spans_to_sequence(spans, ["english_cleaners"])
    ctrl = expand_word_controls(spans, [1.0, 3.0])
    assert len(ctrl) == len(seq) == 2
    np.testing.assert_allclose(ctrl, [1.0, 3.0])  # word b keeps its factor


def test_pad_control():
    out = pad_control(np.asarray([2.0, 3.0], np.float32), 5)
    np.testing.assert_allclose(out, [[2, 3, 1, 1, 1]])


@pytest.mark.slow
def test_per_phone_duration_control_changes_length():
    """A [B, L] duration-control array must flow through the jitted forward
    and scale predicted durations per phone."""
    import jax

    from speakingstyle_tpu.models.factory import build_model, init_variables

    cfg = tiny_config()
    model = build_model(cfg)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    B, L, T = 1, 6, 48
    rng = np.random.default_rng(0)
    kw = dict(
        speakers=np.zeros((B,), np.int32),
        texts=rng.integers(1, 300, (B, L)).astype(np.int32),
        src_lens=np.full((B,), L, np.int32),
        mels=rng.standard_normal((B, T, 80)).astype(np.float32),
        mel_lens=np.full((B,), T, np.int32),
        max_mel_len=T,
        deterministic=True,
    )
    apply = lambda **c: model.apply(
        {"params": variables["params"],
         "batch_stats": variables.get("batch_stats", {})}, **kw, **c)
    base = apply()
    uniform = apply(d_control=2.0)
    per_phone = apply(d_control=np.full((B, L), 2.0, np.float32))
    # scalar 2.0 and all-2.0 per-phone array must agree exactly
    np.testing.assert_array_equal(
        np.asarray(uniform["durations"]), np.asarray(per_phone["durations"])
    )
    # uneven per-phone control shifts duration mass to the scaled phone
    half = np.ones((B, L), np.float32)
    half[:, 0] = 3.0
    uneven = apply(d_control=half)
    d_base = np.asarray(base["durations"])
    d_uneven = np.asarray(uneven["durations"])
    np.testing.assert_array_equal(d_uneven[:, 1:], d_base[:, 1:])
    assert (d_uneven[:, 0] >= d_base[:, 0]).all()


# ---------------------------------------------------------------------------
# Synthesis utils
# ---------------------------------------------------------------------------

def test_expand():
    from speakingstyle_tpu.synthesis import expand

    np.testing.assert_allclose(
        expand(np.asarray([1.0, 2.0, 3.0]), np.asarray([2, 0, 3])),
        [1, 1, 3, 3, 3],
    )


def test_plot_mel_smoke():
    from speakingstyle_tpu.synthesis import plot_mel

    rng = np.random.default_rng(0)
    fig = plot_mel(
        [(rng.standard_normal((80, 50)), rng.standard_normal(50),
          rng.standard_normal(50))],
        [-2.0, 9.0, 150.0, 40.0, -1.5, 8.0],
        ["test"],
    )
    assert fig is not None
    import matplotlib.pyplot as plt

    plt.close(fig)


@pytest.mark.slow
def test_get_vocoder_random_init_and_infer():
    from speakingstyle_tpu.synthesis import get_vocoder
    from speakingstyle_tpu.models.hifigan import vocoder_infer

    cfg = tiny_config()
    gen, params = get_vocoder(cfg, ckpt_path=None)
    mels = np.zeros((2, 16, 80), np.float32)
    wavs = vocoder_infer(gen, params, mels, lengths=[10, 16])
    assert wavs[0].shape == (10 * 256,) and wavs[1].shape == (16 * 256,)
    assert wavs[0].dtype == np.int16


@pytest.mark.slow
def test_synth_samples_griffin_lim(tmp_path, synthetic_preprocessed):
    """Vocoder-free path writes playable wavs + plots for every real item."""
    import jax

    from speakingstyle_tpu.data import BucketedBatcher, SpeechDataset
    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.synthesis import synth_one_sample, synth_samples

    cfg = tiny_config(
        preprocess=PreprocessConfig(
            path=PathConfig(preprocessed_path=synthetic_preprocessed)
        ),
    )
    model = build_model(cfg)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    ds = SpeechDataset("val.txt", cfg, sort=False, drop_last=False)
    batcher = BucketedBatcher(ds, max_src=96, max_mel=96)
    batch = next(batcher.epoch(shuffle=False))
    arrays = batch.arrays()
    out = model.apply(
        {"params": variables["params"],
         "batch_stats": variables.get("batch_stats", {})},
        speakers=arrays["speakers"], texts=arrays["texts"],
        src_lens=arrays["src_lens"], mels=arrays["mels"],
        mel_lens=arrays["mel_lens"], max_mel_len=arrays["mels"].shape[1],
        p_targets=arrays["pitches"], e_targets=arrays["energies"],
        d_targets=arrays["durations"], deterministic=True,
    )
    paths = synth_samples(batch, out, None, cfg, str(tmp_path), plot=True)
    assert len(paths) == batch.n_real
    import scipy.io.wavfile

    sr, wav = scipy.io.wavfile.read(paths[0])
    assert sr == 22050 and wav.dtype == np.int16 and len(wav) > 0
    assert os.path.exists(os.path.join(str(tmp_path), f"{batch.ids[0]}.png"))

    fig, wav_recon, wav_pred, name = synth_one_sample(batch, out, None, cfg)
    assert wav_recon.dtype == np.int16 and name == batch.ids[0]
    import matplotlib.pyplot as plt

    plt.close(fig)


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

def test_cli_parsers_build():
    from speakingstyle_tpu.__main__ import main

    with pytest.raises(SystemExit):  # no command
        main([])
    with pytest.raises(SystemExit):  # help works
        main(["train", "--help"])


@pytest.mark.slow
def test_cli_train_smoke(tmp_path, synthetic_preprocessed, monkeypatch):
    """python -m speakingstyle_tpu train on the synthetic dataset."""
    import yaml

    from speakingstyle_tpu.__main__ import main

    pre = {"path": {"preprocessed_path": synthetic_preprocessed}}
    mdl = {
        "transformer": {"encoder_layer": 1, "decoder_layer": 1,
                        "encoder_hidden": 32, "decoder_hidden": 32,
                        "conv_filter_size": 64},
        "reference_encoder": {"encoder_layer": 1, "encoder_hidden": 32,
                              "conv_filter_size": 64},
        "variance_predictor": {"filter_size": 32},
        "variance_embedding": {"n_bins": 16},
        "max_seq_len": 96,
    }
    trn = {
        "path": {"ckpt_path": str(tmp_path / "ckpt"),
                 "log_path": str(tmp_path / "log"),
                 "result_path": str(tmp_path / "result")},
        "optimizer": {"batch_size": 4},
        "step": {"total_step": 2, "log_step": 1, "val_step": 100,
                 "save_step": 2, "synth_step": 100},
    }
    paths = {}
    for name, doc in (("preprocess", pre), ("model", mdl), ("train", trn)):
        p = tmp_path / f"{name}.yaml"
        p.write_text(yaml.safe_dump(doc))
        paths[name] = str(p)
    main(["train", "-p", paths["preprocess"], "-m", paths["model"],
          "-t", paths["train"], "--max_steps", "2", "--data_parallel", "1"])
    assert (tmp_path / "ckpt" / "2").exists()
    assert "Step 1" in (tmp_path / "log" / "log.txt").read_text()

    # evaluate restores the checkpoint it just wrote
    losses = main(["evaluate", "-p", paths["preprocess"], "-m", paths["model"],
                   "-t", paths["train"]])
    assert "total_loss" in losses


@pytest.mark.slow
def test_trainer_default_synth_callback(tmp_path, synthetic_preprocessed):
    """run_training with synth_callback='default' renders a sample and logs
    throughput without error."""
    from speakingstyle_tpu.training.trainer import run_training

    cfg = tiny_config(
        preprocess=PreprocessConfig(
            path=PathConfig(preprocessed_path=synthetic_preprocessed)
        ),
        train=TrainConfig(
            path=TrainPathConfig(
                ckpt_path=str(tmp_path / "ckpt"),
                log_path=str(tmp_path / "log"),
                result_path=str(tmp_path / "result"),
            ),
        ),
    )
    object.__setattr__(cfg.train.optimizer, "batch_size", 4)
    object.__setattr__(cfg.train.step, "total_step", 2)
    object.__setattr__(cfg.train.step, "log_step", 1)
    object.__setattr__(cfg.train.step, "synth_step", 2)
    object.__setattr__(cfg.train.step, "val_step", 100)
    object.__setattr__(cfg.train.step, "save_step", 100)
    state = run_training(cfg, max_steps=2, synth_callback="default")
    assert int(state.step) == 2
    log = (tmp_path / "log" / "log.txt").read_text()
    assert "[perf] Step" in log and "mel-frames/s" in log


@pytest.mark.slow
def test_cli_analyze_all_modes(tmp_path, capsys):
    """`analyze` productizes the reference's variance-distribution and
    ref-encoder notebooks: features, predictions (free-running), style.
    The two model-dependent modes analyze a REAL (briefly trained)
    checkpoint, not a random init: a 2-step train leg saves a ckpt that
    analyze restores (VERDICT r4 #8)."""
    import json as _json

    import yaml

    from speakingstyle_tpu.__main__ import main
    from speakingstyle_tpu.data.synthetic import generate_corpus

    corpus = str(tmp_path / "corpus")
    generate_corpus(corpus, n_utts=18, val_utts=5,
                    n_phones_per_utt=(8, 12), duration_range=(2, 4))
    docs = {
        "preprocess": {"path": {"preprocessed_path": corpus}},
        "model": {"transformer": {"encoder_layer": 1, "decoder_layer": 1,
                                  "encoder_hidden": 32, "decoder_hidden": 32,
                                  "conv_filter_size": 64},
                  "reference_encoder": {"encoder_layer": 1,
                                        "encoder_hidden": 32,
                                        "conv_filter_size": 64},
                  "variance_predictor": {"filter_size": 32},
                  "variance_embedding": {"n_bins": 16},
                  "max_seq_len": 96},
        "train": {"path": {"ckpt_path": str(tmp_path / "ckpt"),
                           "log_path": str(tmp_path / "log"),
                           "result_path": str(tmp_path / "res")},
                  "optimizer": {"batch_size": 4},
                  "step": {"total_step": 2, "save_step": 2, "log_step": 1,
                           "val_step": 100, "synth_step": 10**9}},
    }
    cargs = []
    for name, doc in docs.items():
        p = tmp_path / f"{name}.yaml"
        p.write_text(yaml.safe_dump(doc))
        cargs += [{"preprocess": "-p", "model": "-m", "train": "-t"}[name],
                  str(p)]

    feats = main(["analyze", *cargs, "--what", "features"])
    assert feats["pitch"]["count"] > 0 and feats["duration"]["count"] > 0

    # a real checkpoint for the model-dependent modes
    main(["train", *cargs, "--max_steps", "2", "--data_parallel", "1"])
    capsys.readouterr()

    preds = main(["analyze", *cargs, "--what", "predictions",
                  "--max_batches", "2"])
    assert "restored checkpoint @ step 2" in capsys.readouterr().out
    assert preds["pitch"]["pred"]["count"] > 0
    # non-degenerate true-vs-predicted histogram overlap from real weights
    assert 0.0 < preds["pitch"]["hist_overlap"] <= 1.0

    out_json = str(tmp_path / "style.json")
    style = main(["analyze", *cargs, "--what", "style", "--max_batches", "2",
                  "--json", out_json])
    assert style["n_utts"] > 0
    gates = style["film_gates"]
    assert any(k.endswith("s_gamma") for k in gates)
    assert any(k.endswith("s_beta") for k in gates)
    assert _json.load(open(out_json))["what"] == "style"
