"""Long-form synthesis (tier-1): chunker, stitcher, service, ring.

Five layers, mirroring serving/longform.py:
  1. chapter chunker — determinism, exact packing, giant-sentence /
     empty / unicode edges (pure python, no jax);
  2. prosodic stitcher — equal-power seam bit-math against a
     monolithic full-buffer reference, bounded memory (numpy only);
  3. service orchestration — deadline-sharing chunk groups, bounded
     in-flight depth, ring->chunked degradation via the
     ``longform_ring_error`` fault kind (fake backend, no jax);
  4. router semantics — a chapter group's deadline_ms override in the
     EDF heap under contention, and the max_deadline_ms clamp;
  5. tiny-model e2e — HTTP structured 413 with the /synthesize/longform
     pointer, the chunked endpoint end-to-end, and the ring tier
     matching the unsharded dense free-run with zero steady-state
     compiles (real jax, 2-way seq mesh on the forced-8-device CPU).
"""

import dataclasses
import http.client
import json
import threading
import time
from collections import deque
from types import SimpleNamespace

import numpy as np
import pytest

from speakingstyle_tpu.configs.config import (
    Config,
    FleetConfig,
    LongformConfig,
    ModelConfig,
    ReferenceEncoderConfig,
    ServeConfig,
    StyleConfig,
    TransformerConfig,
    VarianceEmbeddingConfig,
    VariancePredictorConfig,
)
from speakingstyle_tpu.faults import FaultPlan
from speakingstyle_tpu.obs import JsonlEventLog, MetricsRegistry, read_events
from speakingstyle_tpu.serving.engine import SynthesisRequest
from speakingstyle_tpu.serving.lattice import RequestTooLarge
from speakingstyle_tpu.serving.longform import (
    LongformService,
    Stitcher,
    plan_chunks,
    split_sentences,
)

# ---------------------------------------------------------------------------
# chapter chunker (no jax)
# ---------------------------------------------------------------------------


def _enc(ids_per_word=3):
    """Deterministic fake G2P: every whitespace word costs ``ids_per_word``
    phonemes, values derived from the text so repeats are detectable."""
    def encode(text):
        n = len(text.split()) * ids_per_word
        return (np.arange(n, dtype=np.int32) % 61) + 1
    return encode


def test_split_sentences_unicode_and_punct():
    text = "こんにちは。\n今日は良い天気です。 Bonjour! Ça va? Fin…  ok."
    assert split_sentences(text) == [
        "こんにちは。", "今日は良い天気です。", "Bonjour!", "Ça va?",
        "Fin…", "ok.",
    ]
    # no sentence-final punctuation: one sentence (plan_chunks hard-splits)
    assert split_sentences("no punctuation at all") == \
        ["no punctuation at all"]
    assert split_sentences("") == []
    assert split_sentences("   \n\t ") == []


def test_plan_chunks_deterministic_and_exactly_packed():
    text = " ".join(f"alpha beta s{i}." for i in range(7))  # 9 ids/sentence
    a = plan_chunks(text, _enc(), max_phonemes=20)
    b = plan_chunks(text, _enc(), max_phonemes=20)
    assert len(a) == len(b) >= 2
    for ca, cb in zip(a, b):
        assert ca.index == cb.index and ca.text == cb.text
        np.testing.assert_array_equal(ca.sequence, cb.sequence)
    # exact packing: chunk sequences ARE the concatenated sentence
    # sequences — nothing re-estimated, nothing lost
    whole = np.concatenate(
        [_enc()(s) for s in split_sentences(text)]
    )
    np.testing.assert_array_equal(
        np.concatenate([c.sequence for c in a]), whole
    )
    for c in a:
        assert 0 < c.sequence.size <= 20
        assert c.sequence.dtype == np.int32
    # greedy: every chunk but the last could not absorb the next sentence
    for c, nxt in zip(a, a[1:]):
        first_sent_ids = 9  # every sentence is 3 words
        assert c.sequence.size + first_sent_ids > 20


def test_plan_chunks_one_giant_sentence_hard_splits():
    seq = np.arange(1, 38, dtype=np.int32)  # 37 ids, no boundary to cut
    chunks = plan_chunks("one giant sentence no punct",
                         lambda s: seq, max_phonemes=10)
    assert [c.sequence.size for c in chunks] == [10, 10, 10, 7]
    np.testing.assert_array_equal(
        np.concatenate([c.sequence for c in chunks]), seq
    )
    assert [c.index for c in chunks] == [0, 1, 2, 3]


def test_plan_chunks_empty_and_unencodable_text():
    assert plan_chunks("", _enc(), 10) == []
    assert plan_chunks("  \n ", _enc(), 10) == []
    # encoder yields nothing (e.g. punctuation-only sentences)
    assert plan_chunks("... ...", lambda s: np.empty(0, np.int32), 10) == []
    with pytest.raises(ValueError):
        plan_chunks("x", _enc(), 0)


def test_plan_chunks_admission_cap_raises_413():
    text = " ".join(f"w{i}." for i in range(30))  # 30 sentences, 3 ids each
    with pytest.raises(RequestTooLarge, match="max_chunks"):
        plan_chunks(text, _enc(), max_phonemes=3, max_chunks=8)
    # uncapped plans fine
    assert len(plan_chunks(text, _enc(), max_phonemes=3)) == 30


# ---------------------------------------------------------------------------
# prosodic stitcher (numpy only)
# ---------------------------------------------------------------------------


def _reference_stitch(wavs, fade):
    """Monolithic full-buffer crossfade: the O(chapter)-memory math the
    streaming Stitcher must reproduce bit-for-bit."""
    out = np.asarray(wavs[0], np.int16)
    for w in wavs[1:]:
        w = np.asarray(w, np.int16)
        f = min(fade, out.size, w.size)
        if f > 0:
            th = (np.arange(f, dtype=np.float32) + 0.5) * (np.pi / (2 * f))
            mixed = np.clip(
                out[-f:].astype(np.float32) * np.cos(th)
                + w[:f].astype(np.float32) * np.sin(th),
                -32768, 32767,
            ).astype(np.int16)
            out = np.concatenate([out[:-f], mixed, w[f:]])
        else:
            out = np.concatenate([out, w])
    return out


def test_stitcher_matches_monolithic_reference_bit_exactly():
    rng = np.random.default_rng(7)
    fade = 16
    wavs = [
        rng.integers(-20000, 20000, int(n)).astype(np.int16)
        for n in rng.integers(3 * fade, 120, 5)
    ]
    st = Stitcher(fade)
    pieces = []
    for w in wavs:
        pieces.extend(st.feed(w))
    pieces.extend(st.finish())
    got = np.concatenate(pieces)
    np.testing.assert_array_equal(got, _reference_stitch(wavs, fade))
    # one crossfade per seam: total length shrinks by fade per join
    assert got.size == sum(w.size for w in wavs) - (len(wavs) - 1) * fade
    # every seam metered
    assert len(st.seam_rms) == len(wavs) - 1
    assert all(np.isfinite(r) and r >= 0 for r in st.seam_rms)


def test_stitcher_fade_zero_is_a_metered_butt_joint():
    rng = np.random.default_rng(1)
    wavs = [rng.integers(-100, 100, 40).astype(np.int16) for _ in range(3)]
    st = Stitcher(0)
    pieces = []
    for w in wavs:
        pieces.extend(st.feed(w))
    pieces.extend(st.finish())
    np.testing.assert_array_equal(np.concatenate(pieces),
                                  np.concatenate(wavs))
    assert len(st.seam_rms) == 2  # seams still observed (click detector)


def test_stitcher_memory_is_bounded_by_the_fade():
    fade = 8
    st = Stitcher(fade)
    rng = np.random.default_rng(2)
    for _ in range(50):
        st.feed(rng.integers(-5, 5, 64).astype(np.int16))
        assert st._tail is not None and st._tail.size <= fade
    assert st.feed(np.empty(0, np.int16)) == []
    with pytest.raises(ValueError):
        Stitcher(-1)


# ---------------------------------------------------------------------------
# service orchestration (fake backend — no jax)
# ---------------------------------------------------------------------------


class _FakeFrontend:
    """3 phoneme ids per word; no style; numeric speakers."""

    def sequence(self, text):
        return _enc()(text)

    def resolve_style(self, payload):
        return None, None, False

    def speaker(self, spec):
        return int(spec)


class _FakeBackend:
    """submit() hands back lazily-resolving futures with deterministic
    wavs, and records the high-water mark of uncollected futures — the
    bounded-memory observable."""

    def __init__(self):
        self.requests = []
        self.outstanding = 0
        self.max_outstanding = 0
        self.cancelled = 0

    def submit(self, req):
        self.requests.append(req)
        self.outstanding += 1
        self.max_outstanding = max(self.max_outstanding, self.outstanding)
        backend = self
        rng = np.random.default_rng(req.sequence.size + len(self.requests))
        wav = rng.integers(-3000, 3000, req.sequence.size * 4).astype(np.int16)

        class _Fut:
            def result(self, timeout=None):
                backend.outstanding -= 1
                return SimpleNamespace(id=req.id, wav=wav)

            def cancel(self):
                backend.cancelled += 1
                return True

        return _Fut()


def _svc_cfg(**lf_kw):
    lf = dict(crossfade_frames=0, group_depth=2, max_chunks=16,
              deadline_ms_per_chunk=30_000.0)
    lf.update(lf_kw)
    return Config(serve=ServeConfig(
        batch_buckets=[1, 2], src_buckets=[16], mel_buckets=[64],
        frames_per_phoneme=2, longform=LongformConfig(**lf),
    ))


def _chapter(n_sent=6):
    # each sentence = 4 words = 12 ids; cap 16 -> one sentence per chunk
    return {"text": " ".join(f"alpha beta gamma s{i}." for i in range(n_sent))}


def test_service_admission_plans_a_deadline_sharing_group(tmp_path):
    reg = MetricsRegistry()
    be = _FakeBackend()
    svc = LongformService(_svc_cfg(), _FakeFrontend(), be, registry=reg,
                          events=JsonlEventLog(str(tmp_path)))
    assert svc.chunk_phoneme_cap == 16  # min(src 16, mel 64 / fpp 2 = 32)
    plan = svc.admit("lf1", _chapter(6))
    assert plan.tier == "chunked" and len(plan.chunks) == 6
    assert plan.total_phonemes == 72
    # 6 * 30s = 180s exceeds fleet.max_deadline_ms -> clamped group budget
    assert plan.deadline_ms == 120_000.0
    assert svc.admit("lf2", _chapter(2)).deadline_ms == 60_000.0
    wav = np.concatenate(list(svc.stream(plan)))
    # every chunk request carries the chapter's identity: same arrival,
    # same shared deadline override, the long-form class, ordered ids
    assert [r.id for r in be.requests] == [f"lf1.c{i:03d}" for i in range(6)]
    assert all(r.priority == "batch" for r in be.requests)
    assert all(r.arrival == plan.arrival for r in be.requests)
    assert all(r.deadline_ms == plan.deadline_ms for r in be.requests)
    assert wav.size == 72 * 4  # crossfade 0: nothing trimmed
    assert reg.value("serve_longform_requests_total",
                     {"tier": "chunked"}) == 2.0
    assert reg.value("serve_longform_chunks_total") == 6.0
    names = [r["event"] for r in read_events(str(tmp_path))]
    assert names == ["longform_admit", "longform_admit", "longform_done"]


def test_service_in_flight_depth_is_bounded(tmp_path):
    be = _FakeBackend()
    svc = LongformService(_svc_cfg(group_depth=2), _FakeFrontend(), be,
                          registry=MetricsRegistry())
    plan = svc.admit("lf1", _chapter(7))
    assert len(plan.chunks) == 7
    for _ in svc.stream(plan):
        pass
    # never more than group_depth chunk futures ahead of the stitch point
    assert be.max_outstanding == 2


def test_service_abandoned_stream_cancels_pending_chunks():
    be = _FakeBackend()
    svc = LongformService(_svc_cfg(group_depth=3), _FakeFrontend(), be,
                          registry=MetricsRegistry())
    gen = svc.stream(svc.admit("lf1", _chapter(6)))
    next(gen)       # first stitched piece: group_depth futures in flight
    gen.close()     # consumer hangs up mid-chapter
    assert be.cancelled >= 1
    assert len(be.requests) < 6  # the tail of the chapter was never sent


def test_service_ring_failure_degrades_to_chunked(tmp_path):
    reg = MetricsRegistry()
    be = _FakeBackend()
    svc = LongformService(
        _svc_cfg(), _FakeFrontend(), be,
        engine=SimpleNamespace(vocoder=("gen", "params")),
        ring=SimpleNamespace(max_src=10_000, max_mel=100_000),
        fault_plan=FaultPlan.parse("longform_ring_error@1"),
        registry=reg, events=JsonlEventLog(str(tmp_path)),
    )
    plan = svc.admit("lf1", _chapter(4))
    assert plan.tier == "ring"  # fits the (stub) ring lattice
    wav = np.concatenate(list(svc.stream(plan)))
    # PR 9 contract: the injected ring fault costs one degradation, not
    # the request — the chapter completes on the chunked tier
    assert plan.tier == "chunked"
    assert wav.size == plan.total_phonemes * 4
    assert len(be.requests) == 4
    assert reg.value("serve_longform_degraded_total") == 1.0
    assert reg.value("serve_longform_requests_total", {"tier": "ring"}) == 1.0
    assert reg.value("serve_longform_requests_total",
                     {"tier": "chunked"}) == 1.0
    names = [r["event"] for r in read_events(str(tmp_path))]
    assert names == ["longform_admit", "longform_degraded", "longform_done"]
    assert svc.fault_plan.pending() == []  # fired exactly once


def test_service_admission_validation():
    svc = LongformService(_svc_cfg(), _FakeFrontend(), _FakeBackend(),
                          registry=MetricsRegistry())
    with pytest.raises(ValueError, match="text"):
        svc.admit("x", {})
    with pytest.raises(ValueError, match="tier"):
        svc.admit("x", {"text": "hi there.", "tier": "warp"})
    with pytest.raises(ValueError, match="scalar"):
        svc.admit("x", {"text": "hi there.",
                        "duration_control": [1.0, 2.0]})
    with pytest.raises(RequestTooLarge):
        svc.admit("x", _chapter(40))  # 40 chunks > max_chunks=16
    # no ring attached: forcing tier=ring still admits as chunked
    assert svc.admit(
        "x", {"text": "hi there.", "tier": "ring"}
    ).tier == "chunked"


# ---------------------------------------------------------------------------
# router semantics: the deadline_ms override in the EDF heap (no jax)
# ---------------------------------------------------------------------------


def _fleet_cfg(**fleet_kw):
    fleet = dict(queue_depth=32)
    fleet.update(fleet_kw)
    return Config(serve=ServeConfig(
        batch_buckets=[1], src_buckets=[16], mel_buckets=[64],
        frames_per_phoneme=2, max_wait_ms=5.0,
        fleet=FleetConfig(**fleet),
    ))


class _GatedEngine:
    """Replica stand-in: records dispatch order, gate blocks the first."""

    def __init__(self, gate):
        self.dispatches = []
        self.gate = gate
        self.entered = threading.Event()
        self._first = True
        self.lock = threading.Lock()

    def precompile(self):
        return 0.0

    def run(self, requests):
        if self._first:
            self._first = False
            self.entered.set()
            self.gate.wait(timeout=10)
        with self.lock:
            self.dispatches.extend(r.id for r in requests)
        return [SimpleNamespace(id=r.id, bucket=None, mel_len=1)
                for r in requests]


def _rreq(rid, **kw):
    return SynthesisRequest(
        id=rid, sequence=np.ones(8, np.int32),
        ref_mel=np.zeros((4, 80), np.float32), **kw,
    )


def test_chapter_group_rides_the_edf_heap_as_one_late_unit():
    """Chunks sharing one arrival + one deadline_ms override sort after
    plain batch work (their budget is the CHAPTER's, not the class's)
    and keep their submission order among themselves."""
    from speakingstyle_tpu.serving.fleet import FleetRouter

    gate = threading.Event()
    eng = _GatedEngine(gate)
    router = FleetRouter(lambda reg: eng, _fleet_cfg(), replicas=1)
    assert router.wait_ready(timeout=10)
    futs = [router.submit(_rreq("r0"))]          # occupies the worker
    assert eng.entered.wait(timeout=10)
    t0 = time.monotonic()
    # a 2-chunk chapter group (50 s shared budget), then ordinary traffic
    for c in ("lf.c000", "lf.c001"):
        futs.append(router.submit(_rreq(
            c, priority="batch", arrival=t0, deadline_ms=50_000.0)))
    futs.append(router.submit(_rreq("b1", priority="batch")))
    futs.append(router.submit(_rreq("i1", priority="interactive")))
    gate.set()
    for f in futs:
        f.result(timeout=10)
    router.close()
    # EDF: interactive (250 ms) < batch (2 s) < the chapter group (50 s);
    # FIFO inside the group — the stitcher needs chunks in order
    assert eng.dispatches == ["r0", "i1", "b1", "lf.c000", "lf.c001"]


def test_deadline_override_is_clamped_and_validated():
    from speakingstyle_tpu.serving.fleet import FleetRouter

    cfg = _fleet_cfg(max_deadline_ms=90_000.0)
    router = FleetRouter(lambda reg: _GatedEngine(threading.Event()),
                         cfg, replicas=1)
    try:
        # no override: the class budget
        assert router._budget_s(_rreq("a"), "interactive") == 0.25
        # override below the ceiling: taken verbatim
        assert router._budget_s(
            _rreq("b", deadline_ms=500.0), "batch") == 0.5
        # a client cannot park an entry in the heap forever
        assert router._budget_s(
            _rreq("c", deadline_ms=1e9), "batch") == 90.0
        with pytest.raises(ValueError, match="deadline_ms"):
            router.submit(_rreq("d", deadline_ms=-1.0))
    finally:
        router.close(flush=False)
    # the ceiling must admit every class budget
    with pytest.raises(ValueError, match="max_deadline_ms"):
        FleetConfig(max_deadline_ms=100.0)  # < batch's 2000 ms


# ---------------------------------------------------------------------------
# tiny-model e2e: HTTP 413 pointer, chunked endpoint, ring tier (real jax)
# ---------------------------------------------------------------------------


def _tiny_cfg():
    return Config(
        model=ModelConfig(
            transformer=TransformerConfig(
                encoder_layer=1, decoder_layer=1, encoder_hidden=16,
                decoder_hidden=16, conv_filter_size=16,
                conv_kernel_size=(3, 1),
            ),
            reference_encoder=ReferenceEncoderConfig(
                encoder_layer=1, encoder_head=2, encoder_hidden=16,
                conv_layer=1, conv_filter_size=16,
            ),
            variance_predictor=VariancePredictorConfig(filter_size=16),
            variance_embedding=VarianceEmbeddingConfig(n_bins=8),
            postnet_embedding_dim=16, postnet_layers=2,
            max_seq_len=48, compute_dtype="float32",
        ),
        serve=ServeConfig(
            batch_buckets=[1, 2], src_buckets=[16], mel_buckets=[32],
            frames_per_phoneme=2, max_wait_ms=20.0,
            style=StyleConfig(ref_buckets=[32]),
            longform=LongformConfig(
                crossfade_frames=1, group_depth=2, max_chunks=32,
                deadline_ms_per_chunk=30_000.0,
            ),
        ),
    )


@pytest.fixture(scope="module")
def tiny_serve():
    """(cfg, variables, engine): one precompiled tiny engine shared by
    the e2e tests (AOT precompile is the expensive part)."""
    import jax

    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.models.hifigan import Generator
    from speakingstyle_tpu.serving.engine import SynthesisEngine

    cfg = _tiny_cfg()
    model = build_model(cfg, n_position=49)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    # bias the duration predictor so random weights predict ~2 frames
    # per phoneme — real (nonzero) audio flows end-to-end
    bias = variables["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"]
    variables["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"] = bias + 1.1
    gen = Generator(
        upsample_rates=(2, 2), upsample_kernel_sizes=(4, 4),
        upsample_initial_channel=16, resblock_kernel_sizes=(3,),
        resblock_dilation_sizes=((1,),),
    )
    gparams = gen.init(
        jax.random.PRNGKey(0), np.zeros((1, 8, 80), np.float32)
    )["params"]
    engine = SynthesisEngine(cfg, variables, vocoder=(gen, gparams),
                             model=model)
    engine.precompile()
    return cfg, variables, engine


@pytest.fixture(scope="module")
def ring_tier(tiny_serve):
    """A 2-way seq-mesh ring tier over the tiny model's weights, at one
    dedicated long-form bucket (32 phonemes / 64 mel frames)."""
    from speakingstyle_tpu.serving.longform import RingTier

    cfg, variables, engine = tiny_serve
    cfg_lf = dataclasses.replace(cfg, serve=dataclasses.replace(
        cfg.serve, longform=LongformConfig(
            mesh_seq=2, src_buckets=[32], mel_buckets=[64],
            crossfade_frames=1, deadline_ms_per_chunk=30_000.0,
        ),
    ))
    ring = RingTier(cfg_lf, variables, engine)
    ring.precompile()
    return ring


def _http(server):
    host, port = server.address[:2]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return http.client.HTTPConnection(host, port, timeout=60)


def test_http_too_large_is_a_structured_413_with_longform_pointer(tiny_serve):
    from speakingstyle_tpu.serving.server import SynthesisServer, TextFrontend

    cfg, _, engine = tiny_serve
    ref = np.random.default_rng(0).standard_normal((20, 80)).astype(np.float32)
    server = SynthesisServer(engine, TextFrontend(cfg, ref),
                             host="127.0.0.1", port=0)
    try:
        conn = _http(server)
        # far past the 16-phoneme lattice ceiling
        conn.request("POST", "/synthesize", body=json.dumps(
            {"text": "the quick brown fox jumps over the lazy dog "
                     "again and again while twenty tired turtles "
                     "slowly carry seven shiny stones home"}
        ))
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 413
        assert body["max_src"] == 16 and body["max_mel"] == 32
        assert body["max_phonemes"] == 16  # min(src 16, mel 32 / fpp 2)
        assert body["longform"] == "/synthesize/longform"
        assert resp.getheader("X-Request-Id")
        conn.close()
    finally:
        server.shutdown()


def test_http_longform_chunked_end_to_end(tiny_serve):
    from speakingstyle_tpu.serving.engine import CompileMonitor
    from speakingstyle_tpu.serving.server import SynthesisServer, TextFrontend

    cfg, _, engine = tiny_serve
    ref = np.random.default_rng(0).standard_normal((20, 80)).astype(np.float32)
    server = SynthesisServer(engine, TextFrontend(cfg, ref),
                             host="127.0.0.1", port=0)
    assert server.longform is not None  # auto-built with the frontend
    text = ("The quick brown fox jumps over the lazy dog. "
            "Pack my box with five dozen liquor jugs. "
            "How vexingly quick daft zebras jump!")
    try:
        conn = _http(server)
        with CompileMonitor() as mon:
            conn.request("POST", "/synthesize/longform",
                         body=json.dumps({"text": text}))
            resp = conn.getresponse()
            body = resp.read()
        assert resp.status == 200, body
        assert resp.getheader("Content-Type") == "audio/wav"
        assert resp.getheader("X-Longform-Tier") == "chunked"
        assert int(resp.getheader("X-Longform-Chunks")) >= 2
        assert body[:4] == b"RIFF" and body[8:12] == b"WAVE"
        assert len(body) > 44  # header + stitched audio
        # the acceptance invariant holds through the chapter path: every
        # chunk rode a precompiled interactive bucket
        assert mon.count == 0, "long-form synthesis compiled in steady state"

        # malformed chapter -> structured 400, server stays up
        conn.request("POST", "/synthesize/longform", body=json.dumps({}))
        resp = conn.getresponse()
        assert resp.status == 400 and b"text" in resp.read()
        conn.close()
    finally:
        server.shutdown()


def test_ring_tier_matches_dense_free_run_zero_steady_state_compiles(
        tiny_serve, ring_tier):
    """Tier (b) correctness: the 2-way ring-attention chapter free-run
    reproduces the unsharded dense model at the same padded geometry,
    and repeat chapters execute with ZERO compiles."""
    import jax

    from speakingstyle_tpu.models.factory import build_model
    from speakingstyle_tpu.serving.engine import CompileMonitor

    cfg, variables, engine = tiny_serve
    ring = ring_tier
    rng = np.random.default_rng(3)
    n = 24  # past the interactive src bucket (16), inside the ring's 32
    seq = rng.integers(1, 300, n).astype(np.int32)
    ref = rng.standard_normal((20, 80)).astype(np.float32)
    sv = engine.style.encode_mels([ref])[0]

    req = SynthesisRequest(id="ch0", sequence=seq, ref_mel=None, style=sv)
    result = ring.synthesize(req)
    assert result.bucket.l_src == 32 and result.bucket.t_mel == 64
    assert 0 < result.mel_len <= 64
    assert result.mel.shape == (result.mel_len, 80)
    assert result.wav is None  # mel-only: the vocoder streams it

    # unsharded dense reference at the identical padded geometry
    dense = build_model(cfg, n_position=ring.lattice.max_mel + 1)
    texts = np.zeros((1, 32), np.int32)
    texts[0, :n] = seq
    out = dense.apply(
        variables,
        speakers=np.zeros((1,), np.int32),
        texts=texts,
        src_lens=np.asarray([n], np.int32),
        mels=None, mel_lens=None, max_mel_len=64,
        p_control=np.ones((1, 32), np.float32),
        e_control=np.ones((1, 32), np.float32),
        d_control=np.ones((1, 32), np.float32),
        gammas=sv.gamma.reshape(1, 1, -1),
        betas=sv.beta.reshape(1, 1, -1),
        deterministic=True,
    )
    assert int(np.asarray(out["mel_lens"])[0]) == result.mel_len
    np.testing.assert_allclose(
        result.mel, np.asarray(out["mel_postnet"])[0, :result.mel_len],
        atol=2e-4,
    )

    # steady state: a second chapter reuses the ring program
    with CompileMonitor() as mon:
        again = ring.synthesize(
            SynthesisRequest(id="ch1", sequence=seq, ref_mel=None, style=sv)
        )
    assert mon.count == 0, "ring tier compiled in steady state"
    np.testing.assert_allclose(again.mel, result.mel, atol=1e-5)

    # the compile minted a ProgramCard on the shared registry
    card = engine.program_registry.card("acoustic_ring:b1.s32.m64")
    assert card is not None and card["flops"] > 0
    assert card["label_kind"] == "acoustic_ring"
    assert card["label_mesh"] == "seq2"


def test_http_longform_ring_tier_selected_at_admission(tiny_serve, ring_tier):
    """Attaching a ring tier (cli/serve.py style) flips small chapters
    to tier (b) at admission; the response streams through the engine's
    precompiled vocoder windows and names its tier."""
    from speakingstyle_tpu.serving.server import SynthesisServer, TextFrontend

    cfg, _, engine = tiny_serve
    ref = np.random.default_rng(0).standard_normal((20, 80)).astype(np.float32)
    server = SynthesisServer(engine, TextFrontend(cfg, ref),
                             host="127.0.0.1", port=0)
    server.longform.ring = ring_tier
    try:
        conn = _http(server)
        conn.request("POST", "/synthesize/longform",
                     body=json.dumps({"text": "Hello there friend."}))
        resp = conn.getresponse()
        body = resp.read()
        assert resp.status == 200, body
        assert resp.getheader("X-Longform-Tier") == "ring"
        assert body[:4] == b"RIFF" and len(body) > 44
        conn.close()
    finally:
        server.shutdown()
