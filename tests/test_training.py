"""Training-subsystem tests: sharded steps, loop, checkpointing, restore."""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from speakingstyle_tpu.configs.config import (
    PathConfig,
    StepConfig,
    TrainPathConfig,
    load_config,
)
from speakingstyle_tpu.models.factory import build_model, count_params, init_variables
from speakingstyle_tpu.parallel import make_mesh
from speakingstyle_tpu.training import (
    CheckpointManager,
    TrainState,
    make_eval_step,
    make_optimizer,
    make_train_step,
    run_training,
)


def tiny_train_config(root, tmp_path, batch_size=8):
    cfg = load_config(preset="LJSpeech")
    tf = dataclasses.replace(
        cfg.model.transformer,
        encoder_layer=1, decoder_layer=1,
        encoder_hidden=32, decoder_hidden=32, conv_filter_size=64,
    )
    ref = dataclasses.replace(
        cfg.model.reference_encoder,
        encoder_layer=1, encoder_hidden=32, conv_filter_size=32, encoder_head=2,
    )
    vp = dataclasses.replace(cfg.model.variance_predictor, filter_size=32)
    mc = dataclasses.replace(
        cfg.model, transformer=tf, reference_encoder=ref, variance_predictor=vp,
        max_seq_len=256, compute_dtype="float32",
    )
    pp = dataclasses.replace(cfg.preprocess, path=PathConfig(preprocessed_path=root))
    opt = dataclasses.replace(cfg.train.optimizer, batch_size=batch_size)
    steps = StepConfig(total_step=4, log_step=2, synth_step=100, val_step=3, save_step=4)
    paths = TrainPathConfig(
        ckpt_path=str(tmp_path / "ckpt"),
        log_path=str(tmp_path / "log"),
        result_path=str(tmp_path / "result"),
    )
    tr = dataclasses.replace(
        cfg.train, optimizer=opt, step=steps, path=paths
    )
    return dataclasses.replace(cfg, preprocess=pp, model=mc, train=tr)


@pytest.mark.slow
def test_count_params_and_init(synthetic_preprocessed, tmp_path):
    cfg = tiny_train_config(synthetic_preprocessed, tmp_path)
    model = build_model(cfg)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    n = count_params(variables["params"])
    assert n > 1000
    assert "batch_stats" in variables


@pytest.mark.slow
def test_sharded_train_step_runs_and_descends(synthetic_preprocessed, tmp_path):
    cfg = tiny_train_config(synthetic_preprocessed, tmp_path)
    mesh = make_mesh()  # 8 virtual devices
    model = build_model(cfg)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    tx = make_optimizer(cfg.train)
    state = TrainState.create(variables, tx)
    from jax.sharding import NamedSharding, PartitionSpec as P

    state = jax.device_put(state, NamedSharding(mesh, P()))
    train_step = make_train_step(model, tx, cfg, mesh=mesh)

    from speakingstyle_tpu.data import BucketedBatcher, DevicePrefetcher, SpeechDataset

    ds = SpeechDataset("train.txt", cfg, sort=True, drop_last=True)
    batcher = BucketedBatcher(ds, max_src=256, max_mel=256)
    pf = DevicePrefetcher(iter(batcher), mesh=mesh)
    rng = jax.random.PRNGKey(1)
    losses_hist = []
    for i, (batch, arrays) in enumerate(pf):
        if i >= 6:
            break
        state, losses = train_step(state, arrays, rng)
        losses_hist.append(float(losses["total_loss"]))
    pf.stop()
    assert int(state.step) == 6
    assert all(np.isfinite(losses_hist))
    assert losses_hist[-1] < losses_hist[0]


@pytest.mark.slow
def test_run_training_end_to_end_with_checkpoint(synthetic_preprocessed, tmp_path):
    cfg = tiny_train_config(synthetic_preprocessed, tmp_path)
    state = run_training(cfg, mesh=make_mesh(), max_steps=4, log=True)
    assert int(state.step) == 4
    # checkpoint written at step 4
    ckpt = CheckpointManager(cfg.train.path.ckpt_path)
    assert ckpt.latest_step() == 4
    # log.txt written
    log_file = os.path.join(cfg.train.path.log_path, "log.txt")
    assert os.path.exists(log_file) and "Step" in open(log_file).read()

    # restore round-trips exactly
    model = build_model(cfg)
    variables = init_variables(model, cfg, jax.random.PRNGKey(cfg.train.seed))
    tx = make_optimizer(cfg.train)
    fresh = TrainState.create(variables, tx)
    restored = ckpt.restore(fresh)
    assert int(restored.step) == 4
    got = jax.tree_util.tree_leaves(restored.params)
    want = jax.tree_util.tree_leaves(jax.device_get(state).params)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
    ckpt.close()


def test_restore_ignore_layers(synthetic_preprocessed, tmp_path):
    cfg = tiny_train_config(synthetic_preprocessed, tmp_path)
    model = build_model(cfg)
    tx = make_optimizer(cfg.train)
    v1 = init_variables(model, cfg, jax.random.PRNGKey(0))
    state1 = TrainState.create(v1, tx).replace(step=jnp.asarray(7, jnp.int32))
    ckpt = CheckpointManager(str(tmp_path / "ck2"))
    ckpt.save(7, state1)

    v2 = init_variables(model, cfg, jax.random.PRNGKey(99))
    fresh = TrainState.create(v2, tx)
    restored = ckpt.restore(fresh, ignore_layers=["speaker_emb|mel_linear"])
    # mel_linear kept fresh
    np.testing.assert_array_equal(
        np.asarray(restored.params["mel_linear"]["kernel"]),
        np.asarray(v2["params"]["mel_linear"]["kernel"]),
    )
    # encoder loaded from checkpoint
    got = jax.tree_util.tree_leaves(restored.params["encoder"])
    want = jax.tree_util.tree_leaves(v1["params"]["encoder"])
    assert any(
        not np.array_equal(a, b)
        for a, b in zip(
            jax.tree_util.tree_leaves(v2["params"]["encoder"]), want
        )
    )  # sanity: the two inits differ
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
    ckpt.close()


@pytest.mark.slow
def test_train_step_bfloat16(synthetic_preprocessed, tmp_path):
    """The production compute dtype (bfloat16) compiles and descends on CPU.

    The multi-chip dry run deliberately runs float32 for compile speed
    (__graft_entry__._dryrun_config); this is the paired bf16 smoke so the
    shipping dtype path stays exercised."""
    cfg = tiny_train_config(synthetic_preprocessed, tmp_path)
    cfg = dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, compute_dtype="bfloat16")
    )
    model = build_model(cfg)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    tx = make_optimizer(cfg.train)
    state = TrainState.create(variables, tx)
    train_step = make_train_step(model, tx, cfg, mesh=None)

    rng = np.random.default_rng(0)
    B, L, T = 4, 8, 16
    batch = dict(
        speakers=jnp.zeros((B,), jnp.int32),
        texts=jnp.asarray(rng.integers(1, 300, (B, L)), jnp.int32),
        src_lens=jnp.full((B,), L, jnp.int32),
        mels=jnp.asarray(rng.standard_normal((B, T, 80)), jnp.float32),
        mel_lens=jnp.full((B,), T, jnp.int32),
        pitches=jnp.asarray(rng.standard_normal((B, L)), jnp.float32),
        energies=jnp.asarray(rng.standard_normal((B, L)), jnp.float32),
        durations=jnp.full((B, L), T // L, jnp.int32),
    )
    key = jax.random.PRNGKey(1)
    first = None
    for _ in range(3):
        state, losses = train_step(state, batch, key)
        total = float(losses["total_loss"])
        assert np.isfinite(total)
        if first is None:
            first = total
    assert total < first  # descends under bf16 too


@pytest.mark.slow
def test_training_descends_on_learnable_synthetic_corpus(tmp_path):
    """Short replay of the committed descent artifact
    (artifacts/train_descent_r4, scripts/train_descent.py): on the
    learnable synthetic corpus (data/synthetic.py) the real run_training
    loop must drive the loss clearly below its early value, across a
    checkpoint+resume boundary."""
    import dataclasses

    from speakingstyle_tpu.configs.config import (
        OptimizerConfig,
        StepConfig,
        TrainConfig,
        TrainPathConfig,
    )
    from speakingstyle_tpu.data.synthetic import generate_corpus
    from speakingstyle_tpu.training.trainer import run_training
    from tests.test_models import tiny_config

    corpus = str(tmp_path / "corpus")
    generate_corpus(corpus, n_utts=40, val_utts=4,
                    n_phones_per_utt=(10, 14), duration_range=(2, 4))

    cfg = tiny_config()
    cfg = dataclasses.replace(
        cfg,
        preprocess=dataclasses.replace(
            cfg.preprocess,
            path=dataclasses.replace(
                cfg.preprocess.path, preprocessed_path=corpus
            ),
        ),
        train=TrainConfig(
            path=TrainPathConfig(
                ckpt_path=str(tmp_path / "ckpt"),
                log_path=str(tmp_path / "log"),
                result_path=str(tmp_path / "res"),
            ),
            # init_lr=anneal_lr=1e-3: the reference ramp would still be at
            # lr~1e-4 by step 40, far too cold for a 40-step descent check
            optimizer=OptimizerConfig(
                batch_size=8, init_lr=1e-3, anneal_lr=1e-3
            ),
            step=StepConfig(total_step=40, log_step=5, val_step=1000,
                            save_step=20, synth_step=10**9),
        ),
    )
    run_training(cfg, max_steps=20)
    run_training(cfg, restore_step=-1, max_steps=40)

    log = (tmp_path / "log" / "log.txt").read_text().splitlines()
    losses = {}
    for ln in log:
        # format: "[train] Step N, total_loss: X, mel_loss: ..., lr: ..."
        if ln.startswith("[train] Step ") and "total_loss:" in ln:
            step = int(ln.split("Step ")[1].split(",")[0])
            losses[step] = float(ln.split("total_loss: ")[1].split(",")[0])
    assert 5 in losses and 40 in losses, sorted(losses)
    early = losses[5]
    late = min(losses[s] for s in losses if s > 30)
    assert late < 0.7 * early, (early, late, losses)


@pytest.mark.parametrize("impl", ["flat", "leaf"])
@pytest.mark.parametrize("weight_decay,grad_acc", [(0.0, 1), (0.01, 1), (0.0, 2)])
def test_fused_optimizer_matches_chain(weight_decay, grad_acc, impl):
    """Both fused optimizers (flat raveled-vector and r5's per-leaf fused
    chain) produce the same parameter trajectory as the optax chain —
    including the global-norm clip engaging (step with large grads), bias
    correction, the LR schedule's step indexing, the L2-before-moments
    weight decay, and the MultiSteps grad-accumulation wrapper."""
    import optax

    from speakingstyle_tpu.configs.config import TrainConfig
    from speakingstyle_tpu.training.optim import make_optimizer

    cfg = TrainConfig()
    cfg = dataclasses.replace(
        cfg,
        optimizer=dataclasses.replace(
            cfg.optimizer, weight_decay=weight_decay, grad_acc_step=grad_acc
        ),
    )
    rng = np.random.default_rng(0)
    params = {
        "a": {"w": jnp.asarray(rng.standard_normal((7, 5)), jnp.float32)},
        "b": jnp.asarray(rng.standard_normal(11), jnp.float32),
    }
    tx_chain = make_optimizer(cfg)
    tx_fused = make_optimizer(
        dataclasses.replace(cfg, fused_optimizer=impl)
    )
    s_chain = tx_chain.init(params)
    s_fused = tx_fused.init(params)
    p_chain = p_fused = params
    for i in range(4):
        scale = 100.0 if i == 1 else 0.1  # step 1 triggers the norm clip
        grads = jax.tree_util.tree_map(
            lambda p: scale * jnp.asarray(
                rng.standard_normal(p.shape), jnp.float32
            ),
            params,
        )
        u1, s_chain = tx_chain.update(grads, s_chain, p_chain)
        p_chain = optax.apply_updates(p_chain, u1)
        u2, s_fused = tx_fused.update(grads, s_fused, p_fused)
        p_fused = optax.apply_updates(p_fused, u2)
        for a, b in zip(
            jax.tree_util.tree_leaves(p_chain),
            jax.tree_util.tree_leaves(p_fused),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7,
            )


@pytest.mark.slow
def test_fused_optimizer_trains(synthetic_preprocessed, tmp_path):
    """fused_optimizer=True through the real train step: loss decreases."""
    cfg = tiny_train_config(synthetic_preprocessed, tmp_path)
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, fused_optimizer=True)
    )
    model = build_model(cfg)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    tx = make_optimizer(cfg.train)
    state = TrainState.create(variables, tx)
    train_step = make_train_step(model, tx, cfg, mesh=None)

    from speakingstyle_tpu.data import BucketedBatcher, SpeechDataset

    ds = SpeechDataset("train.txt", cfg, sort=True, drop_last=True)
    batcher = BucketedBatcher(ds, max_src=256, max_mel=256)
    rng = jax.random.PRNGKey(1)
    losses_hist = []
    for i, b in enumerate(iter(batcher)):
        if i >= 6:
            break
        state, losses = train_step(state, b.arrays(), rng)
        losses_hist.append(float(losses["total_loss"]))
    assert all(np.isfinite(losses_hist))
    assert losses_hist[-1] < losses_hist[0]
