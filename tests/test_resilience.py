"""Fault-tolerance suite (tier-1): every recovery path exercised end-to-end
on CPU via deterministic fault injection (training/faults.py).

Layers:
  1. unit — FaultPlan grammar, retry_io backoff, Quarantine budget,
     GracefulShutdown signal plumbing, all_finite, RollbackGuard;
  2. components — DevicePrefetcher shutdown/terminal contract, dataset
     loader retry + batcher quarantine, CheckpointManager async saves,
     retention, and corrupt-directory restore fallback;
  3. end-to-end — run_training drills: NaN rollback (with and without a
     checkpoint to roll back to), consecutive-rollback abort, loader
     IOError retry, SIGTERM flush + gapless ``restore_step=-1`` resume,
     and the final-checkpoint-on-tail-steps guarantee.
"""

import dataclasses
import os
import signal
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from speakingstyle_tpu.configs.config import (
    PathConfig,
    ResilienceConfig,
    StepConfig,
    TrainPathConfig,
    load_config,
)
from speakingstyle_tpu.data import BucketedBatcher, DevicePrefetcher, SpeechDataset
from speakingstyle_tpu.training import faults
from speakingstyle_tpu.training.checkpoint import CheckpointManager
from speakingstyle_tpu.training.faults import FaultPlan
from speakingstyle_tpu.training.resilience import (
    BadSampleBudgetError,
    GracefulShutdown,
    Quarantine,
    RollbackGuard,
    TrainingDivergedError,
    all_finite,
    retry_io,
)
from speakingstyle_tpu.training.trainer import run_training


# ---------------------------------------------------------------------------
# 1. units
# ---------------------------------------------------------------------------


def test_fault_plan_grammar_and_fire_once():
    plan = FaultPlan.parse("loader_ioerror@7; nan_grads@12;sigterm@20")
    assert plan and len(plan.pending()) == 3
    assert not plan.fire("nan_grads", 11)
    assert plan.fire("nan_grads", 12)
    assert not plan.fire("nan_grads", 12)  # exactly once
    assert plan.pending() == [("loader_ioerror", 7), ("sigterm", 20)]
    assert not FaultPlan.parse("")
    # duplicates are distinct entries (poisons the post-rollback replay)
    dup = FaultPlan.parse("nan_grads@3;nan_grads@3")
    assert dup.fire("nan_grads", 3) and dup.fire("nan_grads", 3)
    assert not dup.fire("nan_grads", 3)


@pytest.mark.parametrize("bad", ["nan_grads", "nan_grads@x", "typo@3"])
def test_fault_plan_rejects_bad_specs(bad):
    with pytest.raises(ValueError):
        FaultPlan.parse(bad)


def test_fault_plan_from_env(monkeypatch):
    monkeypatch.setenv(faults.ENV_VAR, "sigterm@5")
    assert FaultPlan.from_env().pending() == [("sigterm", 5)]
    monkeypatch.delenv(faults.ENV_VAR)
    assert not FaultPlan.from_env()


def test_retry_io_recovers_with_exponential_backoff():
    calls, sleeps = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise IOError("transient")
        return "ok"

    assert retry_io(flaky, retries=3, backoff=0.1, sleep=sleeps.append) == "ok"
    assert len(calls) == 3
    assert sleeps == [0.1, 0.2]  # doubles per attempt


def test_retry_io_final_failure_propagates():
    def always():
        raise IOError("permanent")

    with pytest.raises(IOError, match="permanent"):
        retry_io(always, retries=2, backoff=0.0, sleep=lambda _: None)


def test_quarantine_budget():
    q = Quarantine(budget=2)
    q.add("a", ValueError("x"))
    q.add("b", ValueError("y"))
    assert len(q) == 2 and "a" in q and "c" not in q
    with pytest.raises(BadSampleBudgetError):
        q.add("c", ValueError("z"))


def test_graceful_shutdown_catches_and_restores():
    before = signal.getsignal(signal.SIGTERM)
    with GracefulShutdown() as s:
        assert s.installed and not s.requested
        os.kill(os.getpid(), signal.SIGTERM)
        assert s.requested and s.signame == "SIGTERM"
    assert signal.getsignal(signal.SIGTERM) is before


def test_all_finite_reduction():
    ok = {"a": jnp.ones(3), "ints": jnp.arange(4)}  # int leaves ignored
    assert bool(all_finite(ok))
    assert not bool(all_finite(ok, {"b": jnp.array([1.0, jnp.nan])}))
    assert not bool(all_finite({"b": jnp.array([jnp.inf])}))
    # traceable: usable inside the jitted step
    jitted = jax.jit(lambda t: all_finite(t))
    assert not bool(jitted({"x": jnp.array([jnp.nan])}))
    assert bool(jitted({"x": jnp.array([0.5])}))


def test_rollback_guard_consecutive_semantics():
    g = RollbackGuard(max_rollbacks=2)
    assert g.trip(10) == 1
    g.ok()  # a finite window resets the count
    assert g.trip(20) == 1
    assert g.trip(30) == 2
    with pytest.raises(TrainingDivergedError):
        g.trip(40)


def test_poison_batch_nans_only_mels():
    arrays = {"mels": jnp.ones((2, 4, 3)), "texts": jnp.ones((2, 5), jnp.int32)}
    out = faults.poison_batch(arrays)
    assert not bool(jnp.isfinite(out["mels"]).any())
    assert bool(jnp.all(out["texts"] == 1))
    assert bool(jnp.isfinite(arrays["mels"]).all())  # input untouched


# ---------------------------------------------------------------------------
# 2a. DevicePrefetcher shutdown contract
# ---------------------------------------------------------------------------


class _FakeBatch:
    def arrays(self):
        return {"x": np.zeros((2,), np.float32)}


def _infinite_batches():
    while True:
        yield _FakeBatch()


def test_prefetcher_stop_unblocks_blocked_worker():
    """The old worker deadlock: queue full, consumer gone, stop() drains
    once and the worker re-blocks forever on queue.put. The stop-aware
    bounded put must let stop() terminate the thread."""
    pf = DevicePrefetcher(_infinite_batches(), depth=1)
    next(pf)  # worker is now racing to refill the depth-1 queue
    pf.stop()
    assert not pf.thread.is_alive()
    pf.stop()  # idempotent


def test_prefetcher_single_terminal_item_on_error():
    """The old double-enqueue: an exception pushed BOTH the error and the
    None sentinel. Now the error IS the terminal item."""

    def source():
        yield _FakeBatch()
        raise RuntimeError("loader died")

    pf = DevicePrefetcher(source(), depth=4)
    next(pf)
    with pytest.raises(RuntimeError, match="loader died"):
        next(pf)
    with pytest.raises(StopIteration):
        next(pf)  # terminal: nothing queued behind the error
    pf.thread.join(timeout=5.0)
    assert not pf.thread.is_alive()
    assert pf.queue.empty()


def test_prefetcher_clean_end_and_reuse_of_next():
    pf = DevicePrefetcher(iter([_FakeBatch(), _FakeBatch()]), depth=4)
    assert len(list(pf)) == 2
    with pytest.raises(StopIteration):
        next(pf)


def test_prefetcher_context_manager_stops_thread():
    with DevicePrefetcher(_infinite_batches(), depth=1) as pf:
        next(pf)
    assert not pf.thread.is_alive()


# ---------------------------------------------------------------------------
# 2b. dataset retry + quarantine
# ---------------------------------------------------------------------------


def _data_config(root, batch_size=8):
    cfg = load_config(preset="LJSpeech")
    pp = dataclasses.replace(
        cfg.preprocess, path=PathConfig(preprocessed_path=root)
    )
    opt = dataclasses.replace(cfg.train.optimizer, batch_size=batch_size)
    tr = dataclasses.replace(cfg.train, optimizer=opt)
    return dataclasses.replace(cfg, preprocess=pp, train=tr)


def test_loader_retry_recovers_injected_ioerror(synthetic_preprocessed):
    cfg = _data_config(synthetic_preprocessed)
    plan = FaultPlan.parse("loader_ioerror@3")
    ds = SpeechDataset(
        "train.txt", cfg, retries=2, backoff=0.0, fault_plan=plan
    )
    items = [ds[i] for i in range(2)]  # 8 feature loads; #3 faults once
    assert len(items) == 2 and not plan.pending()


def test_loader_without_retries_propagates(synthetic_preprocessed):
    cfg = _data_config(synthetic_preprocessed)
    ds = SpeechDataset(
        "train.txt", cfg, retries=0,
        fault_plan=FaultPlan.parse("loader_ioerror@2"),
    )
    with pytest.raises(OSError):
        [ds[i] for i in range(2)]


def test_batcher_quarantines_corrupt_sample(synthetic_preprocessed):
    root = synthetic_preprocessed
    # permanently corrupt one sample's mel file (retries can't help)
    with open(os.path.join(root, "mel", "LJSpeech-mel-utt003.npy"), "wb") as f:
        f.write(b"not a numpy file")
    cfg = _data_config(synthetic_preprocessed)
    ds = SpeechDataset("train.txt", cfg)
    q = Quarantine(budget=2)
    batcher = BucketedBatcher(ds, max_src=256, max_mel=256, quarantine=q)
    total = sum(b.n_real for b in batcher.epoch(shuffle=False))
    assert total == 9  # 10 train samples, 1 skipped
    assert len(q) == 1 and "utt003" in q
    # a second epoch skips the known-bad sample without re-loading it
    loads_before = ds._feature_loads
    assert sum(b.n_real for b in batcher.epoch(shuffle=False)) == 9
    assert ds._feature_loads == loads_before + 9 * 4
    # zero budget: the first bad sample fails the run
    b0 = BucketedBatcher(
        ds, max_src=256, max_mel=256, quarantine=Quarantine(budget=0)
    )
    with pytest.raises(BadSampleBudgetError):
        list(b0.epoch(shuffle=False))


def test_batcher_without_quarantine_fails_fast(synthetic_preprocessed):
    root = synthetic_preprocessed
    with open(os.path.join(root, "mel", "LJSpeech-mel-utt001.npy"), "wb") as f:
        f.write(b"garbage")
    cfg = _data_config(synthetic_preprocessed)
    batcher = BucketedBatcher(
        SpeechDataset("train.txt", cfg), max_src=256, max_mel=256
    )
    with pytest.raises(Exception):
        list(batcher.epoch(shuffle=False))


# ---------------------------------------------------------------------------
# 2c. checkpoint manager: async, retention, corrupt-dir fallback
# ---------------------------------------------------------------------------


def _toy_state(value: float):
    return {
        "step": jnp.asarray(int(value), jnp.int32),
        "w": jnp.full((4,), value, jnp.float32),
    }


def test_async_save_does_not_block_the_step_loop(tmp_path):
    """Acceptance: the step counter advances while a save is in flight.
    The Orbax write is gated on an event we control, so 'in flight' is a
    deterministic state, not a race."""
    ckpt = CheckpointManager(str(tmp_path / "ck"), async_save=True)
    gate, started = threading.Event(), threading.Event()
    orig_write = ckpt._write

    def gated_write(step, host_state, val_loss):
        started.set()
        assert gate.wait(timeout=10.0)
        orig_write(step, host_state, val_loss)

    ckpt._write = gated_write
    t0 = time.perf_counter()
    ckpt.save(1, _toy_state(1.0))  # returns without waiting for the write
    assert time.perf_counter() - t0 < 5.0
    assert started.wait(timeout=10.0) and ckpt.save_in_flight()

    # ... the "training loop" keeps stepping while the write is gated
    step_fn = jax.jit(lambda s: s + 1)
    counter = jnp.zeros((), jnp.int32)
    for _ in range(3):
        counter = step_fn(counter)
    assert int(jax.device_get(counter)) == 3
    assert ckpt.save_in_flight()  # still mid-save: the loop never blocked

    gate.set()
    ckpt.wait()
    assert not ckpt.save_in_flight() and ckpt.latest_step() == 1
    ckpt.close()


def test_async_save_error_surfaces_on_wait(tmp_path):
    ckpt = CheckpointManager(str(tmp_path / "ck"), async_save=True)

    def boom(step, host_state, val_loss):
        raise RuntimeError("disk full")

    ckpt._write = boom
    ckpt.save(1, _toy_state(1.0))
    with pytest.raises(RuntimeError, match="disk full"):
        ckpt.wait()
    ckpt.close()


def test_retention_prunes_but_keeps_best(tmp_path):
    ckpt = CheckpointManager(
        str(tmp_path / "ck"), max_to_keep=2, keep_best=True
    )
    val = {1: 0.5, 2: 0.1, 3: 0.9, 4: 0.8, 5: 0.7}  # best at step 2
    for s in range(1, 6):
        ckpt.save(s, _toy_state(float(s)), val_loss=val[s], block=True)
    assert ckpt.all_steps() == [2, 4, 5]  # newest 2 + pinned best
    assert ckpt.best_step() == 2
    restored = ckpt.restore(_toy_state(0.0), step=2)
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.full(4, 2.0))
    ckpt.close()


def test_retention_without_keep_best(tmp_path):
    ckpt = CheckpointManager(str(tmp_path / "ck"), max_to_keep=3)
    for s in range(1, 6):
        ckpt.save(s, _toy_state(float(s)), val_loss=float(-s), block=True)
    assert ckpt.all_steps() == [3, 4, 5]
    ckpt.close()


def _corrupt_step_dir(root: str, step: int):
    """Simulate a crash mid-write: gut the step's files, keep the dir."""
    import shutil

    step_dir = None
    for name in os.listdir(root):
        if name == str(step) or name.startswith(f"{step}."):
            step_dir = os.path.join(root, name)
    assert step_dir is not None, os.listdir(root)
    for sub in os.listdir(step_dir):
        p = os.path.join(step_dir, sub)
        shutil.rmtree(p) if os.path.isdir(p) else os.unlink(p)


def test_restore_falls_back_past_corrupt_latest(tmp_path):
    root = str(tmp_path / "ck")
    ckpt = CheckpointManager(root)
    ckpt.save(2, _toy_state(2.0), block=True)
    ckpt.save(4, _toy_state(4.0), block=True)
    ckpt.close()
    _corrupt_step_dir(root, 4)

    ckpt = CheckpointManager(root)
    # latest-step resolution (restore_step=-1) survives the corrupt dir
    restored = ckpt.restore(_toy_state(0.0), step=None)
    assert int(restored["step"]) == 2
    # an explicitly requested corrupt step still fails loudly
    if 4 in ckpt.all_steps():
        with pytest.raises(Exception):
            ckpt.restore(_toy_state(0.0), step=4)
    ckpt.close()


def test_restore_empty_dir_raises(tmp_path):
    ckpt = CheckpointManager(str(tmp_path / "ck"))
    with pytest.raises(FileNotFoundError):
        ckpt.restore(_toy_state(0.0))
    ckpt.close()


# ---------------------------------------------------------------------------
# 3. end-to-end drills through run_training
# ---------------------------------------------------------------------------


def _train_config(root, tmp_path, total=6, save=2, log=1, **res_overrides):
    """Supertiny geometry: compile-bound, so keep one bucket + tiny dims."""
    cfg = load_config(preset="LJSpeech")
    tf = dataclasses.replace(
        cfg.model.transformer,
        encoder_layer=1, decoder_layer=1, encoder_hidden=16,
        decoder_hidden=16, encoder_head=2, decoder_head=2,
        conv_filter_size=32,
    )
    ref = dataclasses.replace(
        cfg.model.reference_encoder,
        encoder_layer=1, encoder_hidden=16, conv_layer=1,
        conv_filter_size=32, encoder_head=2,
    )
    vp = dataclasses.replace(cfg.model.variance_predictor, filter_size=16)
    mc = dataclasses.replace(
        cfg.model, transformer=tf, reference_encoder=ref,
        variance_predictor=vp, max_seq_len=128, compute_dtype="float32",
    )
    pp = dataclasses.replace(
        cfg.preprocess, path=PathConfig(preprocessed_path=root)
    )
    opt = dataclasses.replace(cfg.train.optimizer, batch_size=8)
    steps = StepConfig(
        total_step=total, log_step=log, synth_step=10**9,
        val_step=10**9, save_step=save,
    )
    paths = TrainPathConfig(
        ckpt_path=str(tmp_path / "ckpt"),
        log_path=str(tmp_path / "log"),
        result_path=str(tmp_path / "res"),
    )
    res = ResilienceConfig(**res_overrides)
    tr = dataclasses.replace(
        cfg.train, optimizer=opt, step=steps, path=paths, resilience=res
    )
    return dataclasses.replace(cfg, preprocess=pp, model=mc, train=tr)


def _logged_losses(tmp_path):
    log = (tmp_path / "log" / "log.txt").read_text().splitlines()
    out = {}
    for ln in log:
        if ln.startswith("[train] Step ") and "total_loss:" in ln:
            s = int(ln.split("Step ")[1].split(",")[0])
            out[s] = float(ln.split("total_loss: ")[1].split(",")[0])
    return out


def test_nan_rollback_recovers_and_completes(synthetic_preprocessed, tmp_path,
                                             monkeypatch):
    """Acceptance: nan_grads@k rolls back to the last good checkpoint and
    the run completes with a finite final loss."""
    monkeypatch.setenv(faults.ENV_VAR, "nan_grads@3")
    cfg = _train_config(synthetic_preprocessed, tmp_path, total=6, save=2)
    state = run_training(cfg, max_steps=6)
    assert int(state.step) == 6

    log = (tmp_path / "log" / "log.txt").read_text()
    assert "non-finite losses/grads at step 3" in log
    assert "rollback 1/3 to checkpoint step 2" in log
    losses = _logged_losses(tmp_path)
    # steps resumed 3..6 after the rollback; every logged loss is finite
    assert {3, 4, 5, 6} <= set(losses)
    assert all(np.isfinite(v) for v in losses.values())
    ckpt = CheckpointManager(cfg.train.path.ckpt_path)
    assert ckpt.latest_step() == 6
    ckpt.close()


def test_nan_rollback_without_checkpoint_reinitializes(
    synthetic_preprocessed, tmp_path, monkeypatch
):
    monkeypatch.setenv(faults.ENV_VAR, "nan_grads@1")
    cfg = _train_config(synthetic_preprocessed, tmp_path, total=3, save=100)
    state = run_training(cfg, max_steps=3)
    assert int(state.step) == 3
    log = (tmp_path / "log" / "log.txt").read_text()
    assert "fresh init (no checkpoint yet)" in log
    assert all(np.isfinite(v) for v in _logged_losses(tmp_path).values())


def test_consecutive_rollbacks_abort(synthetic_preprocessed, tmp_path,
                                     monkeypatch):
    """The same poison on every post-rollback replay => diverged run."""
    monkeypatch.setenv(
        faults.ENV_VAR, "nan_grads@3;nan_grads@3;nan_grads@3"
    )
    cfg = _train_config(
        synthetic_preprocessed, tmp_path, total=6, save=2, max_rollbacks=2
    )
    with pytest.raises(TrainingDivergedError):
        run_training(cfg, max_steps=6)


def test_loader_ioerror_drill_completes(synthetic_preprocessed, tmp_path,
                                        monkeypatch):
    """Acceptance: loader_ioerror@k retries/quarantines and completes."""
    monkeypatch.setenv(faults.ENV_VAR, "loader_ioerror@7")
    cfg = _train_config(synthetic_preprocessed, tmp_path, total=4, save=4)
    state = run_training(cfg, max_steps=4)
    assert int(state.step) == 4
    assert all(np.isfinite(v) for v in _logged_losses(tmp_path).values())


def test_sigterm_flush_and_gapless_resume(synthetic_preprocessed, tmp_path,
                                          monkeypatch):
    """Acceptance: a SIGTERM'd run leaves a checkpoint from which
    --restore_step -1 resumes to completion with no step gap."""
    monkeypatch.setenv(faults.ENV_VAR, "sigterm@3")
    cfg = _train_config(synthetic_preprocessed, tmp_path, total=6, save=100)
    state = run_training(cfg, max_steps=6)
    assert int(state.step) == 3  # preempted after step 3...
    ckpt = CheckpointManager(cfg.train.path.ckpt_path)
    assert ckpt.latest_step() == 3  # ...but the flush landed
    ckpt.close()
    log = (tmp_path / "log" / "log.txt").read_text()
    assert "SIGTERM: checkpoint flushed at step 3" in log

    monkeypatch.delenv(faults.ENV_VAR)
    state = run_training(cfg, restore_step=-1, max_steps=6)
    assert int(state.step) == 6
    losses = _logged_losses(tmp_path)
    assert set(losses) == {1, 2, 3, 4, 5, 6}  # no gap, no repeat
    ckpt = CheckpointManager(cfg.train.path.ckpt_path)
    assert ckpt.latest_step() == 6
    ckpt.close()


def test_final_checkpoint_covers_tail_steps(synthetic_preprocessed, tmp_path):
    """total_step not divisible by save_step: the tail must not be lost."""
    cfg = _train_config(synthetic_preprocessed, tmp_path, total=5, save=2)
    state = run_training(cfg, max_steps=5)
    assert int(state.step) == 5
    ckpt = CheckpointManager(cfg.train.path.ckpt_path)
    assert ckpt.latest_step() == 5  # 2, 4 periodic + 5 flushed at loop end
    assert set(ckpt.all_steps()) >= {4, 5}
    ckpt.close()
