"""Serving stack: lattice covering, batcher properties, AOT engine smoke.

Three layers, mirroring the package:
  1. lattice — pure-python covering-bucket properties (no jax);
  2. batcher — deadline / coalescing / exactly-once-future properties
     against a fake engine (no jax, millisecond-fast);
  3. engine + server — the tiny-model end-to-end smoke: AOT precompile,
     serve through the batcher and over HTTP, and assert the serve loop
     performed ZERO XLA compiles after warmup (the acceptance invariant,
     checked with a jax.monitoring listener — not just the engine's own
     counter).
"""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from speakingstyle_tpu.configs.config import (
    Config,
    ModelConfig,
    ReferenceEncoderConfig,
    ServeConfig,
    StyleConfig,
    TransformerConfig,
    VarianceEmbeddingConfig,
    VariancePredictorConfig,
)
from speakingstyle_tpu.serving.batcher import (
    ContinuousBatcher,
    Overloaded,
    ShutdownError,
)
from speakingstyle_tpu.serving.engine import (
    CompileMonitor,
    SynthesisRequest,
    _fill_control,
)
from speakingstyle_tpu.serving.lattice import BucketLattice, RequestTooLarge

# ---------------------------------------------------------------------------
# lattice
# ---------------------------------------------------------------------------


def test_lattice_cover_is_elementwise_smallest():
    lat = BucketLattice([1, 4, 8], [16, 32], [64, 128])
    rng = np.random.default_rng(0)
    for _ in range(200):
        n = int(rng.integers(1, 9))
        l = int(rng.integers(1, 33))
        t = int(rng.integers(1, 129))
        got = lat.cover(n, l, t)
        # covers
        assert got.b >= n and got.l_src >= l and got.t_mel >= t
        # and no strictly smaller covering point exists on any axis
        for p in lat.points():
            if p.b >= n and p.l_src >= l and p.t_mel >= t:
                assert got.b <= p.b and got.l_src <= p.l_src \
                    and got.t_mel <= p.t_mel


def test_lattice_too_large_raises_per_axis():
    lat = BucketLattice([1, 4], [16], [64])
    with pytest.raises(RequestTooLarge, match="batch"):
        lat.cover(5, 8, 32)
    with pytest.raises(RequestTooLarge, match="src"):
        lat.cover(1, 17, 32)
    with pytest.raises(RequestTooLarge, match="mel"):
        lat.cover(1, 8, 65)


def test_lattice_points_and_ordering():
    lat = BucketLattice([1, 2], [16], [32, 64])
    pts = lat.points()
    assert len(pts) == len(lat) == 4
    vols = [p.volume for p in pts]
    assert vols == sorted(vols)  # compile order: cheapest first
    assert lat.max_batch == 2 and lat.max_src == 16 and lat.max_mel == 64


def test_lattice_rejects_bad_axes():
    with pytest.raises(ValueError):
        BucketLattice([], [16], [32])
    with pytest.raises(ValueError):
        BucketLattice([4, 1], [16], [32])


# ---------------------------------------------------------------------------
# batcher (fake engine — no jax)
# ---------------------------------------------------------------------------


def _serve_cfg(**kw):
    base = dict(
        batch_buckets=[1, 2, 4], src_buckets=[16], mel_buckets=[64],
        frames_per_phoneme=2, max_wait_ms=40.0, queue_depth=64,
    )
    base.update(kw)
    return ServeConfig(**base)


class FakeEngine:
    """Engine stand-in: records dispatches, optional gate/failure."""

    class _Cfg:
        def __init__(self, serve):
            self.serve = serve

    def __init__(self, serve=None, gate=None, fail=None):
        self.cfg = self._Cfg(serve or _serve_cfg())
        self.lattice = BucketLattice.from_config(self.cfg.serve)
        self.dispatches = []  # (monotonic_time, [request ids])
        self.gate = gate      # threading.Event blocking the FIRST dispatch
        self.entered = threading.Event()  # set when the FIRST run() starts
        self.fail = fail      # exception instance to raise on every run
        self._first = True
        self.lock = threading.Lock()

    def admit(self, request):
        self.lattice.cover(1, len(request.sequence), 1)

    def run(self, requests):
        if self.gate is not None and self._first:
            self._first = False
            self.entered.set()
            self.gate.wait(timeout=10)
        if self.fail is not None:
            raise self.fail
        with self.lock:
            self.dispatches.append(
                (time.monotonic(), [r.id for r in requests])
            )
        return [f"result:{r.id}" for r in requests]


def _req(i, L=8):
    return SynthesisRequest(
        id=f"r{i}", sequence=np.ones(L, np.int32),
        ref_mel=np.zeros((4, 80), np.float32),
    )


def test_batcher_single_request_dispatches_within_max_wait():
    eng = FakeEngine(_serve_cfg(max_wait_ms=25.0))
    with ContinuousBatcher(eng) as b:
        t0 = time.monotonic()
        fut = b.submit(_req(0))
        assert fut.result(timeout=5) == "result:r0"
        dispatch_t, ids = eng.dispatches[0]
        # the lone request must not wait (noticeably) past max_wait
        assert dispatch_t - t0 <= 0.025 + 0.2
        assert ids == ["r0"]


def test_batcher_coalesces_backlog_into_one_dispatch():
    gate = threading.Event()
    eng = FakeEngine(_serve_cfg(max_wait_ms=5.0), gate=gate)
    with ContinuousBatcher(eng) as b:
        first = b.submit(_req(0))  # worker picks it up, blocks on the gate
        assert eng.entered.wait(timeout=5)
        backlog = [b.submit(_req(1 + i)) for i in range(3)]
        gate.set()
        assert first.result(timeout=5) == "result:r0"
        results = [f.result(timeout=5) for f in backlog]
    assert results == ["result:r1", "result:r2", "result:r3"]
    # the backlog coalesced into ONE dispatch (continuous batching),
    # possibly after the gated singleton
    assert [ids for _, ids in eng.dispatches] == [["r0"], ["r1", "r2", "r3"]]
    assert b.occupancy[3] == 1


def test_batcher_respects_max_batch_cap():
    gate = threading.Event()
    eng = FakeEngine(_serve_cfg(max_wait_ms=5.0), gate=gate)
    with ContinuousBatcher(eng) as b:
        futs = [b.submit(_req(i)) for i in range(9)]  # max_batch = 4
        gate.set()
        for f in futs:
            f.result(timeout=5)
    sizes = [len(ids) for _, ids in eng.dispatches]
    assert all(s <= 4 for s in sizes)
    assert sum(sizes) == 9


def test_batcher_requests_never_wait_past_deadline_when_idle():
    """Submit at a trickle slower than max_wait: every dispatch must start
    within max_wait (+scheduling slack) of its request's arrival."""
    eng = FakeEngine(_serve_cfg(max_wait_ms=20.0))
    arrivals = {}
    with ContinuousBatcher(eng) as b:
        futs = []
        for i in range(5):
            arrivals[f"r{i}"] = time.monotonic()
            futs.append(b.submit(_req(i)))
            time.sleep(0.06)  # > max_wait: each request rides alone
        for f in futs:
            f.result(timeout=5)
    for dispatch_t, ids in eng.dispatches:
        for rid in ids:
            assert dispatch_t - arrivals[rid] <= 0.020 + 0.2, (
                f"{rid} waited past its deadline"
            )


def test_batcher_engine_error_fails_only_that_batch():
    eng = FakeEngine(fail=ValueError("boom"))
    with ContinuousBatcher(eng) as b:
        fut = b.submit(_req(0))
        with pytest.raises(ValueError, match="boom"):
            fut.result(timeout=5)
        # the worker survives; later submits still get served
        eng.fail = None
        assert b.submit(_req(1)).result(timeout=5) == "result:r1"


def test_batcher_rejects_oversized_at_submit():
    eng = FakeEngine()
    with ContinuousBatcher(eng) as b:
        with pytest.raises(RequestTooLarge):
            b.submit(_req(0, L=17))  # src bucket max is 16
        # nothing was enqueued for it
        assert b.submit(_req(1)).result(timeout=5) == "result:r1"


def test_batcher_close_flushes_admitted_requests():
    gate = threading.Event()
    eng = FakeEngine(_serve_cfg(max_wait_ms=5.0), gate=gate)
    b = ContinuousBatcher(eng)
    futs = [b.submit(_req(i)) for i in range(6)]
    gate.set()
    b.close()  # flush=True: every admitted request resolves with a result
    assert [f.result(timeout=0) for f in futs] == [
        f"result:r{i}" for i in range(6)
    ]
    with pytest.raises(ShutdownError):
        b.submit(_req(99))


def test_batcher_close_noflush_fails_pending():
    gate = threading.Event()
    eng = FakeEngine(_serve_cfg(max_wait_ms=5.0), gate=gate)
    b = ContinuousBatcher(eng)
    first = b.submit(_req(0))
    # wait until [r0] is IN FLIGHT (inside engine.run) so the pending
    # submits below cannot coalesce into its batch
    assert eng.entered.wait(timeout=5)
    pending = [b.submit(_req(1 + i)) for i in range(3)]
    b_closer = threading.Thread(target=lambda: b.close(flush=False))
    b_closer.start()
    time.sleep(0.1)
    gate.set()
    b_closer.join(timeout=5)
    assert first.result(timeout=5) == "result:r0"  # in-flight completes
    for f in pending:
        with pytest.raises(ShutdownError):
            f.result(timeout=5)


def test_batcher_futures_resolve_exactly_once_under_racing_shutdown():
    """Hammer submit from several threads while another closes: every
    future that ``submit`` handed out resolves exactly once — with a
    result or ShutdownError — and none is left pending."""
    eng = FakeEngine(_serve_cfg(max_wait_ms=1.0, queue_depth=8))
    b = ContinuousBatcher(eng)
    futures = []
    flock = threading.Lock()
    stop = threading.Event()

    def submitter():
        i = 0
        while not stop.is_set():
            try:
                f = b.submit(_req(i))
            except ShutdownError:
                return
            except Overloaded:  # watermark shed under the hammer: back off
                time.sleep(0.001)
                continue
            with flock:
                futures.append(f)
            i += 1

    threads = [threading.Thread(target=submitter) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.15)
    b.close()
    stop.set()
    for t in threads:
        t.join(timeout=5)

    assert futures, "no request was ever admitted"
    for f in futures:
        assert f.done(), "a submitted future was left pending"
        exc = f.exception(timeout=0)
        assert exc is None or isinstance(exc, ShutdownError)
    served = sum(1 for f in futures if f.exception(timeout=0) is None)
    dispatched = sum(len(ids) for _, ids in eng.dispatches)
    assert served == dispatched  # exactly-once: no result lost or duplicated


def test_batcher_dispatch_events_carry_req_ids(tmp_path):
    """The batcher's serve_dispatch JSONL record lists every request id
    in the coalesced batch — the join key that makes one request's
    records traceable through handler -> batcher -> engine."""
    from speakingstyle_tpu.obs import JsonlEventLog, read_events

    eng = FakeEngine(_serve_cfg(max_wait_ms=5.0))
    log = JsonlEventLog(str(tmp_path))
    with ContinuousBatcher(eng, events=log) as b:
        assert b.submit(_req(0)).result(timeout=5) == "result:r0"
    log.close()
    recs = list(read_events(str(tmp_path), event="serve_dispatch"))
    assert recs and recs[0]["req_ids"] == ["r0"]
    assert recs[0]["rows"] == 1 and recs[0]["duration_s"] >= 0


def test_batcher_stats_are_registry_views():
    """occupancy/dispatched/rejected are views of the registry — the
    snapshot a /metrics scrape sees and the attribute API agree."""
    eng = FakeEngine(_serve_cfg(max_wait_ms=5.0))
    with ContinuousBatcher(eng) as b:
        b.submit(_req(0)).result(timeout=5)
        snap = b.registry.snapshot()
        assert snap["counters"]["serve_batches_total"] == b.dispatched == 1
        assert snap["counters"]['serve_batch_occupancy_total{rows="1"}'] == 1
        assert b.occupancy[1] == 1
        lat = snap["histograms"]["serve_request_latency_seconds"]
        assert lat["count"] == 1 and lat["p50"] is not None


def test_fill_control_scalar_and_per_phoneme():
    # the engine leases the buffer from its pool pre-filled with the
    # neutral 1.0; _fill_control only writes the real rows' prefixes
    out = np.ones((3, 4), np.float32)
    _fill_control([2.0, np.asarray([3.0, 4.0], np.float32)], out)
    np.testing.assert_allclose(out[0], [2, 2, 2, 2])
    np.testing.assert_allclose(out[1], [3, 4, 1, 1])
    np.testing.assert_allclose(out[2], [1, 1, 1, 1])  # padding row neutral


# ---------------------------------------------------------------------------
# engine + server (tiny model, real jax)
# ---------------------------------------------------------------------------


def _tiny_cfg(**serve_kw):
    serve = dict(
        batch_buckets=[1, 2], src_buckets=[16], mel_buckets=[32],
        frames_per_phoneme=2, max_wait_ms=20.0,
        style=StyleConfig(ref_buckets=[32]),
    )
    serve.update(serve_kw)
    return Config(
        model=ModelConfig(
            transformer=TransformerConfig(
                encoder_layer=1, decoder_layer=1, encoder_hidden=16,
                decoder_hidden=16, conv_filter_size=16,
                conv_kernel_size=(3, 1),
            ),
            reference_encoder=ReferenceEncoderConfig(
                encoder_layer=1, encoder_head=2, encoder_hidden=16,
                conv_layer=1, conv_filter_size=16,
            ),
            variance_predictor=VariancePredictorConfig(filter_size=16),
            variance_embedding=VarianceEmbeddingConfig(n_bins=8),
            postnet_embedding_dim=16, postnet_layers=2,
            max_seq_len=48, compute_dtype="float32",
        ),
        serve=ServeConfig(**serve),
    )


@pytest.fixture(scope="module")
def tiny_engine():
    """One precompiled tiny engine shared by the e2e tests (the AOT
    precompile is the expensive part; sharing keeps tier-1 fast)."""
    import jax

    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.models.hifigan import Generator
    from speakingstyle_tpu.serving.engine import SynthesisEngine

    cfg = _tiny_cfg()
    model = build_model(cfg, n_position=49)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    # bias the duration predictor so random weights predict ~2 frames per
    # phoneme — real (nonzero) audio flows end-to-end
    bias = variables["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"]
    variables["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"] = bias + 1.1
    gen = Generator(
        upsample_rates=(2, 2), upsample_kernel_sizes=(4, 4),
        upsample_initial_channel=16, resblock_kernel_sizes=(3,),
        resblock_dilation_sizes=((1,),),
    )
    gparams = gen.init(
        jax.random.PRNGKey(0), np.zeros((1, 8, 80), np.float32)
    )["params"]
    engine = SynthesisEngine(cfg, variables, vocoder=(gen, gparams),
                             model=model)
    engine.precompile()
    return engine


def _mkreq(i, L=10, T=20, rng=None):
    rng = rng or np.random.default_rng(i)
    return SynthesisRequest(
        id=f"utt{i}",
        sequence=rng.integers(1, 300, L).astype(np.int32),
        ref_mel=rng.standard_normal((T, 80)).astype(np.float32),
    )


def test_engine_precompiled_full_lattice(tiny_engine):
    # 2 batch x 1 src x 1 mel acoustic points + 2 vocoder (b, t) pairs
    assert tiny_engine.compile_count == 4
    assert len(tiny_engine._acoustic) == len(tiny_engine.lattice) == 2


def test_serve_smoke_zero_compiles_after_warmup(tiny_engine):
    """The acceptance invariant: after warmup the serve loop performs
    ZERO XLA compiles, measured on the backend's own monitoring bus."""
    engine = tiny_engine
    compiles_before = engine.compile_count
    with ContinuousBatcher(engine) as batcher:
        # warmup: one dispatch per batch bucket
        for b in engine.lattice.batch_buckets:
            engine.run([_mkreq(900 + b * 10 + j) for j in range(b)])
        with CompileMonitor() as mon:
            futs = [batcher.submit(_mkreq(i)) for i in range(7)]
            results = [f.result(timeout=60) for f in futs]
    assert mon.count == 0, "the serve loop compiled after warmup"
    assert engine.compile_count == compiles_before
    # results scattered back to the right requests, audio rendered
    for i, r in enumerate(results):
        assert r.id == f"utt{i}"
        assert r.mel_len > 0          # biased duration predictor
        assert r.wav is not None and r.wav.dtype == np.int16
        assert r.wav.shape == (r.mel_len * 4,)  # tiny vocoder hop = 4
        assert r.mel.shape == (r.mel_len, 80)
        assert r.durations.shape == (10,)
    assert batcher.dispatched >= 1


def test_engine_batch_overflow_rejected_not_split(tiny_engine):
    """More requests than the largest batch bucket cannot form one
    dispatch — cover() refuses (the batcher's max_batch cap prevents this
    by construction; the engine still guards it)."""
    before = tiny_engine.compile_count
    with pytest.raises(RequestTooLarge):
        tiny_engine.cover([_mkreq(50), _mkreq(51), _mkreq(52)])
    assert tiny_engine.compile_count == before


def test_engine_compile_on_miss_is_counted(tiny_engine):
    """Without precompile, the first dispatch compiles (acoustic +
    vocoder) and the engine's counter says so — a lattice miss can never
    be a silent retrace."""
    from speakingstyle_tpu.serving.engine import SynthesisEngine
    from speakingstyle_tpu.serving.lattice import BucketLattice

    engine = SynthesisEngine(
        tiny_engine.cfg, tiny_engine.variables,
        vocoder=tiny_engine.vocoder,
        lattice=BucketLattice([1], [16], [32]),
        model=tiny_engine.model,
    )
    assert engine.compile_count == 0
    with CompileMonitor() as mon:
        engine.run([_mkreq(55)])
    assert engine.compile_count == 2  # acoustic + vocoder, counted
    assert mon.count >= 1             # and visible on the monitoring bus
    with CompileMonitor() as mon:
        engine.run([_mkreq(56)])      # warm now: zero compiles
    assert engine.compile_count == 2 and mon.count == 0


def test_engine_admit_rejects_oversized(tiny_engine):
    with pytest.raises(RequestTooLarge):
        tiny_engine.admit(_mkreq(0, L=17))  # src bucket max 16
    with pytest.raises(RequestTooLarge):
        tiny_engine.admit(_mkreq(0, L=4, T=40))  # mel bucket max 32


def test_engine_per_word_controls_change_output(tiny_engine):
    rng = np.random.default_rng(7)
    base = _mkreq(60, rng=rng)
    slow = SynthesisRequest(
        id="slow", sequence=base.sequence, ref_mel=base.ref_mel,
        d_control=2.0,
    )
    r_base, r_slow = (tiny_engine.run([base])[0], tiny_engine.run([slow])[0])
    assert r_slow.mel_len >= r_base.mel_len
    assert int(r_slow.durations.sum()) >= int(r_base.durations.sum())


def test_http_server_end_to_end(tiny_engine):
    from speakingstyle_tpu.serving.server import SynthesisServer, TextFrontend

    cfg = tiny_engine.cfg
    ref = np.random.default_rng(0).standard_normal((20, 80)).astype(np.float32)
    server = SynthesisServer(
        tiny_engine, TextFrontend(cfg, ref), host="127.0.0.1", port=0
    )
    host, port = server.address[:2]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.request("POST", "/synthesize",
                     body=json.dumps({"text": "hi"}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = resp.read()
        assert resp.status == 200, body
        assert resp.getheader("Content-Type") == "audio/wav"
        assert body[:4] == b"RIFF" and body[8:12] == b"WAVE"

        conn.request("GET", "/healthz")
        health = json.loads(conn.getresponse().read())
        assert health["compile_count"] == tiny_engine.compile_count
        assert health["requests"] == 1
        assert sum(health["batch_occupancy"].values()) >= 1

        # malformed request -> structured 400, server stays up
        conn.request("POST", "/synthesize", body=json.dumps({}))
        resp = conn.getresponse()
        assert resp.status == 400 and b"text" in resp.read()
        conn.close()
    finally:
        server.shutdown()


def test_metrics_endpoint_and_req_id_join(tiny_engine, tmp_path):
    """GET /metrics serves Prometheus text from the engine registry —
    compile counters, queue depth, per-bucket dispatch latency — and the
    req_id minted by the HTTP handler joins its http_request event with
    the batcher's serve_dispatch event (and rides error responses too).
    /healthz must agree with the registry snapshot field-for-field: one
    accounting path."""
    from speakingstyle_tpu.obs import JsonlEventLog, read_events
    from speakingstyle_tpu.serving.server import SynthesisServer, TextFrontend

    ref = np.random.default_rng(0).standard_normal((20, 80)).astype(np.float32)
    log = JsonlEventLog(str(tmp_path))
    server = SynthesisServer(
        tiny_engine, TextFrontend(tiny_engine.cfg, ref),
        host="127.0.0.1", port=0, events=log,
        profile_dir=str(tmp_path / "prof"),
    )
    host, port = server.address[:2]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.request("POST", "/synthesize", body=json.dumps({"text": "hello"}))
        resp = conn.getresponse()
        req_id = resp.getheader("X-Request-Id")
        resp.read()
        assert resp.status == 200 and req_id

        conn.request("GET", "/metrics")
        m = conn.getresponse()
        text = m.read().decode()
        assert m.status == 200
        assert m.getheader("Content-Type").startswith("text/plain")
        assert "serve_compiles_total" in text
        assert "jax_backend_compiles_total" in text
        assert "serve_queue_depth" in text
        # per-bucket dispatch latency histogram (batch-1 covering bucket)
        assert 'serve_dispatch_seconds_bucket{bucket="b1.s16.m32"' in text
        assert 'serve_request_latency_seconds_count' in text
        # ProgramCard gauges minted at compile time, per lattice bucket
        assert 'serve_program_flops{bucket="b1.s16.m32",kind="acoustic"}' \
            in text
        assert 'serve_program_peak_bytes{bucket="b1.s16.m32",kind="acoustic"}' \
            in text
        # the dispatch above fed the achieved-FLOP/s (MFU-style) histogram
        assert 'serve_achieved_flops_per_sec_count{bucket="b1.s16.m32"}' \
            in text
        # persistent-cache counters from the jaxmon bridge (0 on a run
        # with no cache configured, but always exported)
        assert "jax_persistent_cache_hits_total" in text
        assert "jax_persistent_cache_requests_total" in text
        # process identity gauges, sampled at scrape
        assert "process_rss_bytes" in text
        assert "process_uptime_seconds" in text

        # /healthz is a view of the SAME snapshot
        conn.request("GET", "/healthz")
        health = json.loads(conn.getresponse().read())
        snap = server.registry.snapshot()
        assert health["compile_count"] == snap["counters"]["serve_compiles_total"]
        assert health["dispatches"] == snap["counters"]["serve_dispatches_total"]
        assert health["requests"] == snap["counters"]["serve_http_requests_total"]
        assert "queue_depth" in health and "backend_compiles" in health
        # build identity: every probe says WHAT is running
        assert health["build"]["jax"] and health["build"]["backend"]
        assert health["build"]["device_count"] >= 1

        # error responses carry the req_id too (joinable failures)
        conn.request("POST", "/synthesize", body=json.dumps({}))
        bad = conn.getresponse()
        err_id = bad.getheader("X-Request-Id")
        body = json.loads(bad.read())
        assert bad.status == 400 and body["id"] == err_id and err_id != req_id
        conn.close()
    finally:
        server.shutdown()
        log.close()
    (http_rec,) = [r for r in read_events(str(tmp_path), event="http_request")
                   if r["req_id"] == req_id]
    assert http_rec["status"] == 200 and http_rec["duration_s"] > 0
    (dispatch_rec,) = [
        r for r in read_events(str(tmp_path), event="serve_dispatch")
        if req_id in r["req_ids"]
    ]
    assert dispatch_rec["bucket"] == "b1.s16.m32"
    # the failed request produced an http_request event but no dispatch
    err_http = [r for r in read_events(str(tmp_path), event="http_request")
                if r["req_id"] == err_id]
    assert err_http and err_http[0]["status"] == 400
    assert not any(err_id in r["req_ids"] for r in
                   read_events(str(tmp_path), event="serve_dispatch"))


def test_engine_builds_program_cards_at_precompile(tiny_engine):
    """Every compiled executable carries a ProgramCard: one acoustic card
    per lattice point plus the vocoder (b, t) pairs, each with real
    numbers on CPU — and reading them never compiled anything."""
    progs = tiny_engine.programs()
    acoustic = [p for p in progs if p["name"].startswith("acoustic:")]
    vocoder = [p for p in progs if p["name"].startswith("vocoder:")]
    assert len(acoustic) == len(tiny_engine.lattice) == 2
    assert len(vocoder) == 2  # 2 batch buckets x 1 mel bucket
    from speakingstyle_tpu.serving.engine import bucket_label

    assert {p["name"] for p in acoustic} == {
        f"acoustic:{bucket_label(b)}" for b in tiny_engine.lattice.points()
    }
    for p in progs:
        assert p["flops"] > 0 and p["bytes_accessed"] > 0
        assert p["peak_bytes"] > 0 and p["partial"] is False
        json.dumps(p)
    # the bigger batch costs more FLOPs than the smaller one
    by_name = {p["name"]: p for p in acoustic}
    assert by_name["acoustic:b2.s16.m32"]["flops"] > \
        by_name["acoustic:b1.s16.m32"]["flops"]


def test_debug_programs_endpoint(tiny_engine):
    """GET /debug/programs dumps one JSON ProgramCard per compiled
    program, plus the build identity."""
    from speakingstyle_tpu.serving.server import SynthesisServer, TextFrontend

    server = SynthesisServer(
        tiny_engine, TextFrontend(tiny_engine.cfg, None),
        host="127.0.0.1", port=0,
    )
    host, port = server.address[:2]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.request("GET", "/debug/programs")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200
        # engine programs first, then the style-encoder programs once
        assert body["programs"] == (
            tiny_engine.programs() + tiny_engine.style.programs()
        )
        assert len(body["programs"]) == (
            tiny_engine.compile_count + tiny_engine.style.compile_count
        )
        assert body["build"]["backend"]
        conn.close()
    finally:
        server.shutdown()


def test_debug_profile_endpoint(tiny_engine, tmp_path):
    """POST /debug/profile pulls a jax.profiler trace from the live
    process; bad parameters are structured 400s."""
    from speakingstyle_tpu.serving.server import SynthesisServer, TextFrontend

    server = SynthesisServer(
        tiny_engine, TextFrontend(tiny_engine.cfg, None),
        host="127.0.0.1", port=0, profile_dir=str(tmp_path / "prof"),
    )
    host, port = server.address[:2]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection(host, port, timeout=60)
        conn.request("POST", "/debug/profile?seconds=0.2")
        resp = conn.getresponse()
        out = json.loads(resp.read())
        assert resp.status == 200, out
        assert out["seconds"] == 0.2
        import os

        assert os.path.isdir(out["trace_dir"])

        conn.request("POST", "/debug/profile?seconds=bogus")
        resp = conn.getresponse()
        assert resp.status == 400 and b"seconds" in resp.read()

        conn.request("POST", "/debug/profile?seconds=999")
        resp = conn.getresponse()
        assert resp.status == 400 and b"(0, 60]" in resp.read()
        conn.close()
    finally:
        server.shutdown()


def test_render_result_writes_wav(tiny_engine, tmp_path):
    from speakingstyle_tpu.synthesis import render_result

    result = tiny_engine.run([_mkreq(70)])[0]
    path = render_result(result, tiny_engine.cfg, str(tmp_path))
    import scipy.io.wavfile

    sr, wav = scipy.io.wavfile.read(path)
    assert sr == 22050 and wav.dtype == np.int16
    assert len(wav) == result.mel_len * 4


@pytest.mark.slow
def test_offered_load_sweep_runs():
    """The bench.py --serve sweep end-to-end (short duration). The >= 4x
    acceptance number is recorded by the full `python bench.py --serve`
    run (PERF.md "Serving"); here we only require the sweep to complete
    with zero steady-state compiles and a sane ratio."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    ratio = bench.run_serve(duration=0.5, clients=(1, 8))
    assert ratio is not None and ratio > 0
