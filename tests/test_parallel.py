"""Mesh + ring-attention tests on the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from speakingstyle_tpu.parallel import (
    batch_sharding,
    local_batch_size,
    make_mesh,
    make_seq_mesh,
    ring_self_attention,
    shard_batch,
)


def full_attention(q, k, v, bias=None):
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        logits = logits + bias
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


def test_make_mesh_shapes():
    mesh = make_mesh()
    assert mesh.shape["data"] == 8 and mesh.shape["model"] == 1
    mesh = make_mesh(data=4, model=2)
    assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2
    with pytest.raises(ValueError):
        make_mesh(data=3, model=2)
    assert local_batch_size(16, make_mesh()) == 2
    with pytest.raises(ValueError):
        local_batch_size(12, make_mesh())


def test_shard_batch_places_on_mesh():
    mesh = make_mesh()
    batch = {"x": np.ones((16, 5), np.float32), "y": np.zeros((16,), np.int32)}
    out = shard_batch(batch, mesh)
    assert out["x"].sharding == batch_sharding(mesh)
    np.testing.assert_array_equal(np.asarray(out["x"]), batch["x"])


@pytest.mark.parametrize("with_bias", [False, True])
def test_ring_attention_matches_full(with_bias):
    mesh = make_seq_mesh()  # 8-way sequence sharding
    B, H, L, D = 2, 4, 64, 16
    rng = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (B, H, L, D))
    k = jax.random.normal(kk, (B, H, L, D))
    v = jax.random.normal(kv, (B, H, L, D))
    bias = None
    if with_bias:
        # pad out the last 10 key positions of item 1
        pad = jnp.zeros((B, 1, 1, L))
        pad = pad.at[1, :, :, -10:].set(-1e9)
        bias = pad

    out = ring_self_attention(q, k, v, bias, mesh=mesh)
    ref = full_attention(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.slow
def test_ring_attention_grads_flow():
    mesh = make_seq_mesh()
    B, H, L, D = 1, 2, 32, 8
    rng = jax.random.PRNGKey(1)
    q = jax.random.normal(rng, (B, H, L, D))

    def f(q):
        return ring_self_attention(q, q, q, mesh=mesh).sum()

    def f_ref(q):
        return full_attention(q, q, q).sum()

    g = jax.grad(f)(q)
    g_ref = jax.grad(f_ref)(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-4)


def _tiny_cfg():
    from speakingstyle_tpu.configs.config import (
        Config,
        ModelConfig,
        ReferenceEncoderConfig,
        TransformerConfig,
        VariancePredictorConfig,
    )

    return Config(
        model=ModelConfig(
            transformer=TransformerConfig(
                encoder_layer=1, decoder_layer=1,
                encoder_hidden=16, decoder_hidden=16,
                encoder_head=2, decoder_head=2,
                conv_filter_size=32,
            ),
            reference_encoder=ReferenceEncoderConfig(
                encoder_layer=1, conv_layer=1, encoder_hidden=16,
                encoder_head=2, conv_filter_size=16,
            ),
            variance_predictor=VariancePredictorConfig(filter_size=16),
            compute_dtype="float32",
        )
    )


def _tiny_batch(mesh, n_mels=80, B=8, L=8, T=16):
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.default_rng(0)
    batch = dict(
        speakers=jnp.zeros((B,), jnp.int32),
        texts=jnp.asarray(rng.integers(1, 300, (B, L)), jnp.int32),
        src_lens=jnp.full((B,), L, jnp.int32),
        mels=jnp.asarray(rng.standard_normal((B, T, n_mels)), jnp.float32),
        mel_lens=jnp.full((B,), T, jnp.int32),
        pitches=jnp.asarray(rng.standard_normal((B, L)), jnp.float32),
        energies=jnp.asarray(rng.standard_normal((B, L)), jnp.float32),
        durations=jnp.full((B, L), T // L, jnp.int32),
    )
    return {
        k: jax.device_put(v, NamedSharding(mesh, P("data")))
        for k, v in batch.items()
    }


def _run_steps(mesh, state_shardings_fn, n_steps=2, cfg=None):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.training.optim import make_optimizer
    from speakingstyle_tpu.training.state import TrainState
    from speakingstyle_tpu.training.trainer import make_train_step

    cfg = cfg or _tiny_cfg()
    model = build_model(cfg)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    tx = make_optimizer(cfg.train)
    state = TrainState.create(variables, tx)
    sh = state_shardings_fn(state, mesh)
    if sh is None:
        state = jax.device_put(state, NamedSharding(mesh, P()))
    else:
        state = jax.tree_util.tree_map(jax.device_put, state, sh)
    step = make_train_step(model, tx, cfg, mesh=mesh, state_shardings=sh)
    batch = _tiny_batch(mesh)
    losses_out = []
    rng = jax.random.PRNGKey(1)
    for _ in range(n_steps):
        state, losses = step(state, batch, rng)
        losses_out.append(float(losses["total_loss"]))
    return losses_out, state


@pytest.mark.slow
def test_tensor_parallel_matches_data_parallel():
    """(data=4, model=2) TP training must match pure DP loss-for-loss:
    the TP rules only re-layout weights; XLA's collectives must not change
    the math (deterministic=False uses dropout — same fold_in rng both
    ways, same mask)."""
    from speakingstyle_tpu.parallel.partition import (
        count_sharded,
        train_state_shardings,
    )

    losses_dp, _ = _run_steps(make_mesh(data=8, model=1), lambda s, m: None)
    mesh_tp = make_mesh(data=4, model=2)

    def tp_sh(state, mesh):
        return train_state_shardings(state, mesh)

    losses_tp, state_tp = _run_steps(mesh_tp, tp_sh)
    # the TP rules must actually shard something on this model
    assert count_sharded(state_tp.params, mesh_tp) >= 8
    np.testing.assert_allclose(losses_dp, losses_tp, rtol=2e-4)
    # params after TP steps keep their sharded layout (not resharded away)
    from flax.traverse_util import flatten_dict

    flat = flatten_dict(state_tp.params, sep="/")
    specs = {
        k: v.sharding.spec
        for k, v in flat.items()
        if hasattr(v, "sharding")
    }
    assert any("model" in str(s) for s in specs.values())


@pytest.mark.slow
def test_ring_attention_model_level_long_sequence():
    """attention_impl="ring": a 1280-frame mel (beyond max_seq_len=1000)
    through the full FastSpeech2 forward on an 8-way seq mesh matches the
    dense model bit-for-nearly-bit. This is the engaged product path, not
    the isolated kernel (VERDICT r2 weak #5)."""
    import dataclasses

    from speakingstyle_tpu.models.factory import build_model, init_variables

    cfg = _tiny_cfg()
    B, L, T = 2, 64, 1280  # both divide the 8-way seq axis
    cfg_ring = dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, attention_impl="ring")
    )

    dense_model = build_model(cfg, n_position=T + 1)
    variables = init_variables(dense_model, cfg, jax.random.PRNGKey(0))
    ring_model = build_model(
        cfg_ring, n_position=T + 1, seq_mesh=make_seq_mesh()
    )

    rng = np.random.default_rng(0)
    d = T // L
    kwargs = dict(
        speakers=jnp.zeros((B,), jnp.int32),
        texts=jnp.asarray(rng.integers(1, 300, (B, L)), jnp.int32),
        src_lens=jnp.asarray([L, L - 8], jnp.int32),
        mels=jnp.asarray(rng.standard_normal((B, T, 80)), jnp.float32),
        mel_lens=jnp.asarray([T, T - 8 * d], jnp.int32),
        max_mel_len=T,
        p_targets=jnp.asarray(rng.standard_normal((B, L)), jnp.float32),
        e_targets=jnp.asarray(rng.standard_normal((B, L)), jnp.float32),
        d_targets=jnp.full((B, L), d, jnp.int32),
        deterministic=True,
    )
    out_dense = dense_model.apply(variables, **kwargs)
    out_ring = ring_model.apply(variables, **kwargs)
    np.testing.assert_allclose(
        np.asarray(out_ring["mel_postnet"]),
        np.asarray(out_dense["mel_postnet"]),
        atol=2e-4,
    )
    # a ring model must refuse to build without a mesh
    import pytest as _pytest

    with _pytest.raises(ValueError):
        build_model(cfg_ring)


@pytest.mark.slow
def test_production_dims_bf16_aot_compile_tp():
    """AOT lower+compile (NO execute) of the REAL production config —
    default dims (hidden 256, 4+6 layers, ref-encoder 1024 filters),
    bf16 compute — over the (data=4, model=2) mesh at paper batch
    geometry (48 x ~600 frames, SURVEY.md §6).

    The driver's fast dryrun gate uses a toy config (same sharding path,
    shrunk dims); this test is the production-shape evidence: the full
    DPxTP program compiles and GSPMD inserted cross-device all-reduces.
    Abstract args (jax.eval_shape / ShapeDtypeStruct) keep it compile-only.
    """
    import os

    from speakingstyle_tpu.configs.config import Config, ModelConfig
    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.parallel.partition import (
        count_sharded,
        train_state_shardings,
    )
    from speakingstyle_tpu.training.optim import make_optimizer
    from speakingstyle_tpu.training.state import TrainState
    from speakingstyle_tpu.training.trainer import make_train_step

    # persistent compile cache: repeat runs of this (slow) compile are warm
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    cfg = Config(model=ModelConfig(compute_dtype="bfloat16"))
    model = build_model(cfg)
    tx = make_optimizer(cfg.train)

    def make_state(rng):
        return TrainState.create(init_variables(model, cfg, rng), tx)

    abstract_state = jax.eval_shape(make_state, jax.random.PRNGKey(0))
    mesh = make_mesh(data=4, model=2)
    shardings = train_state_shardings(abstract_state, mesh)
    assert count_sharded(abstract_state.params, mesh) > 0

    B, L, T = 48, 100, 600
    f32, i32 = jnp.float32, jnp.int32
    batch = {
        "speakers": jax.ShapeDtypeStruct((B,), i32),
        "texts": jax.ShapeDtypeStruct((B, L), i32),
        "src_lens": jax.ShapeDtypeStruct((B,), i32),
        "mels": jax.ShapeDtypeStruct((B, T, 80), f32),
        "mel_lens": jax.ShapeDtypeStruct((B,), i32),
        "pitches": jax.ShapeDtypeStruct((B, L), f32),
        "energies": jax.ShapeDtypeStruct((B, L), f32),
        "durations": jax.ShapeDtypeStruct((B, L), i32),
    }
    train_step = make_train_step(
        model, tx, cfg, mesh=mesh, state_shardings=shardings
    )
    compiled = train_step.lower(
        abstract_state, batch, jax.random.PRNGKey(1)
    ).compile()

    hlo = compiled.as_text()
    n_ar = hlo.count("all-reduce(") + hlo.count("all-reduce-start(")
    assert n_ar > 0, "no all-reduces in the compiled DPxTP program"
    # TP all-reduces partition over the model axis: with a (4,2) mesh the
    # row-parallel psums use 4 groups of 2 devices
    assert "{{0,1},{2,3},{4,5},{6,7}}" in hlo.replace(" ", ""), (
        "expected model-axis replica groups {{0,1},{2,3},{4,5},{6,7}} "
        "in the HLO"
    )


@pytest.mark.slow
def test_fused_attention_under_sharded_mesh():
    """attention_kernel="fused" inside the data-sharded train step: the
    pallas kernel (interpret mode — FORCE_INTERPRET hook) must run under
    GSPMD with batch-sharded inputs on the 8-device mesh, produce the same
    losses as the einsum path, AND be genuinely batch-partitioned — the
    custom_partitioning rule exists because an unannotated pallas call
    gets its operands ALL-GATHERED (verified in HLO before the fix), a
    silent multichip perf regression. Real-TPU Mosaic lowering of the
    same path is validated on the single-chip mesh (PERF.md)."""
    import dataclasses

    from speakingstyle_tpu.ops import pallas_attention

    cfg = _tiny_cfg()
    cfg_fused = dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, attention_kernel="fused")
    )
    # guard against a vacuous pass: the tiny config's attention shapes
    # must take the kernel path, not the einsum fallback
    tfc = cfg.model.transformer
    assert pallas_attention.supported(
        16, tfc.encoder_hidden // tfc.encoder_head
    )
    mesh = make_mesh(data=8, model=1)
    losses_einsum, _ = _run_steps(mesh, lambda s, m: None, cfg=cfg)
    calls = []
    orig = pallas_attention._pallas_fwd

    def counting_fwd(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)

    pallas_attention.FORCE_INTERPRET = True
    pallas_attention._pallas_fwd = counting_fwd
    try:
        losses_fused, _ = _run_steps(mesh, lambda s, m: None, cfg=cfg_fused)
    finally:
        pallas_attention.FORCE_INTERPRET = False
        pallas_attention._pallas_fwd = orig
    assert calls, "fused path fell back to einsum — test would be vacuous"
    np.testing.assert_allclose(losses_einsum, losses_fused, rtol=2e-4)


@pytest.mark.slow
def test_fused_attention_batch_partitioned_no_allgather():
    """The sharded fwd+bwd HLO of the fused kernel must contain ZERO
    all-gathers: inputs stay batch-sharded through the pallas call and
    gradients come back batch-sharded."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from speakingstyle_tpu.ops import pallas_attention as pa

    mesh = make_mesh(data=8, model=1)
    B, L, H, D = 16, 128, 2, 8
    rng = np.random.default_rng(0)
    sh = NamedSharding(mesh, P("data"))
    q = jax.device_put(
        jnp.asarray(rng.standard_normal((B, L, H, D)), jnp.float32), sh
    )
    mask = jax.device_put(jnp.zeros((B, L), bool), sh)

    pa.FORCE_INTERPRET = True
    try:
        def loss(q):
            return jnp.sum(jnp.square(pa.fused_mha(q, q, q, mask)))

        g = jax.jit(jax.grad(loss), in_shardings=sh)
        hlo = g.lower(q).compile().as_text()
        grads = g(q)
    finally:
        pa.FORCE_INTERPRET = False
    assert "all-gather" not in hlo
    assert grads.sharding.spec == P("data")
