"""Mesh + ring-attention tests on the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from speakingstyle_tpu.parallel import (
    batch_sharding,
    local_batch_size,
    make_mesh,
    make_seq_mesh,
    ring_self_attention,
    shard_batch,
)


def full_attention(q, k, v, bias=None):
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        logits = logits + bias
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


def test_make_mesh_shapes():
    mesh = make_mesh()
    assert mesh.shape["data"] == 8 and mesh.shape["model"] == 1
    mesh = make_mesh(data=4, model=2)
    assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2
    with pytest.raises(ValueError):
        make_mesh(data=3, model=2)
    assert local_batch_size(16, make_mesh()) == 2
    with pytest.raises(ValueError):
        local_batch_size(12, make_mesh())


def test_shard_batch_places_on_mesh():
    mesh = make_mesh()
    batch = {"x": np.ones((16, 5), np.float32), "y": np.zeros((16,), np.int32)}
    out = shard_batch(batch, mesh)
    assert out["x"].sharding == batch_sharding(mesh)
    np.testing.assert_array_equal(np.asarray(out["x"]), batch["x"])


@pytest.mark.parametrize("with_bias", [False, True])
def test_ring_attention_matches_full(with_bias):
    mesh = make_seq_mesh()  # 8-way sequence sharding
    B, H, L, D = 2, 4, 64, 16
    rng = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (B, H, L, D))
    k = jax.random.normal(kk, (B, H, L, D))
    v = jax.random.normal(kv, (B, H, L, D))
    bias = None
    if with_bias:
        # pad out the last 10 key positions of item 1
        pad = jnp.zeros((B, 1, 1, L))
        pad = pad.at[1, :, :, -10:].set(-1e9)
        bias = pad

    out = ring_self_attention(q, k, v, bias, mesh=mesh)
    ref = full_attention(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_grads_flow():
    mesh = make_seq_mesh()
    B, H, L, D = 1, 2, 32, 8
    rng = jax.random.PRNGKey(1)
    q = jax.random.normal(rng, (B, H, L, D))

    def f(q):
        return ring_self_attention(q, q, q, mesh=mesh).sum()

    def f_ref(q):
        return full_attention(q, q, q).sum()

    g = jax.grad(f)(q)
    g_ref = jax.grad(f_ref)(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-4)
