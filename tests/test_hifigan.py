"""HiFi-GAN generator tests: shapes, upsample factor, torch parity.

The parity test builds a small weight-normed torch generator (same topology
as reference hifigan/models.py:112-174), converts its state_dict with
compat.torch_convert, and asserts elementwise agreement — validating both
the conv semantics (padding, transposed-conv equivalence) and the converter
(weight-norm folding, kernel layouts).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn as tnn
from torch.nn.utils import weight_norm

from speakingstyle_tpu.compat.torch_convert import convert_hifigan, fold_weight_norm
from speakingstyle_tpu.models.hifigan import Generator, generator_from_config, vocoder_infer

SMALL = dict(
    upsample_rates=(4, 2),
    upsample_kernel_sizes=(8, 4),
    upsample_initial_channel=16,
    resblock_kernel_sizes=(3, 5),
    resblock_dilation_sizes=((1, 3), (1, 3)),
)


class TorchResBlock(tnn.Module):
    def __init__(self, ch, k, dils):
        super().__init__()
        self.convs1 = tnn.ModuleList(
            [
                weight_norm(tnn.Conv1d(ch, ch, k, 1, dilation=d, padding=(k * d - d) // 2))
                for d in dils
            ]
        )
        self.convs2 = tnn.ModuleList(
            [weight_norm(tnn.Conv1d(ch, ch, k, 1, padding=(k - 1) // 2)) for _ in dils]
        )

    def forward(self, x):
        for c1, c2 in zip(self.convs1, self.convs2):
            y = torch.nn.functional.leaky_relu(x, 0.1)
            y = c1(y)
            y = torch.nn.functional.leaky_relu(y, 0.1)
            y = c2(y)
            x = x + y
        return x


class TorchResBlock2(tnn.Module):
    """Public hifigan models.py ResBlock2 (V2/V3 configs)."""

    def __init__(self, ch, k, dils):
        super().__init__()
        self.convs = tnn.ModuleList(
            [
                weight_norm(tnn.Conv1d(ch, ch, k, 1, dilation=d, padding=(k * d - d) // 2))
                for d in dils
            ]
        )

    def forward(self, x):
        for c in self.convs:
            y = torch.nn.functional.leaky_relu(x, 0.1)
            y = c(y)
            x = x + y
        return x


class TorchGenerator(tnn.Module):
    def __init__(self, cfg, resblock="1"):
        super().__init__()
        ch0 = cfg["upsample_initial_channel"]
        self.conv_pre = weight_norm(tnn.Conv1d(80, ch0, 7, 1, padding=3))
        self.ups = tnn.ModuleList()
        self.resblocks = tnn.ModuleList()
        self.num_kernels = len(cfg["resblock_kernel_sizes"])
        block = TorchResBlock if resblock == "1" else TorchResBlock2
        for i, (u, k) in enumerate(
            zip(cfg["upsample_rates"], cfg["upsample_kernel_sizes"])
        ):
            self.ups.append(
                weight_norm(
                    tnn.ConvTranspose1d(
                        ch0 // (2**i), ch0 // (2 ** (i + 1)), k, u, padding=(k - u) // 2
                    )
                )
            )
            ch = ch0 // (2 ** (i + 1))
            for rk, rd in zip(
                cfg["resblock_kernel_sizes"], cfg["resblock_dilation_sizes"]
            ):
                self.resblocks.append(block(ch, rk, rd))
        self.conv_post = weight_norm(tnn.Conv1d(ch, 1, 7, 1, padding=3))

    def forward(self, mel):  # mel [B, 80, T]
        x = self.conv_pre(mel)
        for i, up in enumerate(self.ups):
            x = torch.nn.functional.leaky_relu(x, 0.1)
            x = up(x)
            xs = None
            for j in range(self.num_kernels):
                y = self.resblocks[i * self.num_kernels + j](x)
                xs = y if xs is None else xs + y
            x = xs / self.num_kernels
        x = torch.nn.functional.leaky_relu(x, 0.1)
        return torch.tanh(self.conv_post(x)).squeeze(1)


def test_generator_shapes():
    gen = Generator(**SMALL)
    mel = jnp.zeros((2, 30, 80))
    params = gen.init(jax.random.PRNGKey(0), mel)["params"]
    wav = gen.apply({"params": params}, mel)
    assert wav.shape == (2, 30 * 4 * 2)


def test_generator_from_config():
    cfg = {
        "upsample_rates": [8, 8, 2, 2],
        "upsample_kernel_sizes": [16, 16, 4, 4],
        "upsample_initial_channel": 32,
        "resblock_kernel_sizes": [3],
        "resblock_dilation_sizes": [[1, 3, 5]],
    }
    gen = generator_from_config(cfg)
    mel = jnp.zeros((1, 10, 80))
    params = gen.init(jax.random.PRNGKey(0), mel)["params"]
    wav = gen.apply({"params": params}, mel)
    assert wav.shape == (1, 10 * 256)


@pytest.mark.parametrize("resblock", ["1", "2"])
def test_torch_parity(resblock):
    torch.manual_seed(0)
    cfg = {k: list(v) if isinstance(v, tuple) else v for k, v in SMALL.items()}
    tgen = TorchGenerator(cfg, resblock=resblock).eval()
    sd = {k: v.detach().numpy() for k, v in tgen.state_dict().items()}
    params = convert_hifigan(sd)

    gen = Generator(**SMALL, resblock=resblock)
    mel = np.random.default_rng(0).standard_normal((2, 17, 80)).astype(np.float32)
    wav_jax = np.asarray(gen.apply({"params": params}, jnp.asarray(mel)))
    with torch.no_grad():
        wav_torch = tgen(torch.from_numpy(mel).transpose(1, 2)).numpy()
    assert wav_jax.shape == wav_torch.shape
    np.testing.assert_allclose(wav_jax, wav_torch, atol=1e-5)


def test_fold_weight_norm_matches_torch():
    torch.manual_seed(1)
    conv = weight_norm(tnn.Conv1d(4, 8, 3))
    sd = {k: v.detach().numpy() for k, v in conv.state_dict().items()}
    folded = fold_weight_norm(sd)
    from torch.nn.utils import remove_weight_norm

    remove_weight_norm(conv)
    np.testing.assert_allclose(
        folded["weight"], conv.weight.detach().numpy(), atol=1e-6
    )


def test_vocoder_infer_trims():
    gen = Generator(**SMALL)
    mel = jnp.zeros((2, 12, 80))
    params = gen.init(jax.random.PRNGKey(0), mel)["params"]
    wavs = vocoder_infer(gen, params, mel, lengths=[5, 12])
    assert len(wavs) == 2
    assert wavs[0].shape == (5 * 8,) and wavs[1].shape == (12 * 8,)


# ---------------------------------------------------------------------------
# MelGAN (the reference's torch.hub vocoder, utils/model.py:64-74)
# ---------------------------------------------------------------------------

def _torch_melgan(n_mels=80, ngf=8, n_residual_layers=2, ratios=(4, 2)):
    """The descript MelGAN generator, replicated layer-for-layer from the
    public mel2wav/modules.py so conversion + forward parity can be tested
    without the hub checkpoint."""

    def WNConv1d(*a, **kw):
        return weight_norm(tnn.Conv1d(*a, **kw))

    def WNConvTranspose1d(*a, **kw):
        return weight_norm(tnn.ConvTranspose1d(*a, **kw))

    class ResnetBlock(tnn.Module):
        def __init__(self, dim, dilation):
            super().__init__()
            self.block = tnn.Sequential(
                tnn.LeakyReLU(0.2),
                tnn.ReflectionPad1d(dilation),
                WNConv1d(dim, dim, kernel_size=3, dilation=dilation),
                tnn.LeakyReLU(0.2),
                WNConv1d(dim, dim, kernel_size=1),
            )
            self.shortcut = WNConv1d(dim, dim, kernel_size=1)

        def forward(self, x):
            return self.shortcut(x) + self.block(x)

    class TorchMelGAN(tnn.Module):
        def __init__(self):
            super().__init__()
            mult = int(2 ** len(ratios))
            model = [
                tnn.ReflectionPad1d(3),
                WNConv1d(n_mels, mult * ngf, kernel_size=7, padding=0),
            ]
            for r in ratios:
                model += [
                    tnn.LeakyReLU(0.2),
                    WNConvTranspose1d(
                        mult * ngf, mult * ngf // 2,
                        kernel_size=r * 2, stride=r,
                        padding=r // 2 + r % 2, output_padding=r % 2,
                    ),
                ]
                for j in range(n_residual_layers):
                    model += [ResnetBlock(mult * ngf // 2, dilation=3**j)]
                mult //= 2
            model += [
                tnn.LeakyReLU(0.2),
                tnn.ReflectionPad1d(3),
                WNConv1d(ngf, 1, kernel_size=7, padding=0),
                tnn.Tanh(),
            ]
            self.model = tnn.Sequential(*model)

        def forward(self, x):
            return self.model(x)

    return TorchMelGAN()


@pytest.mark.parametrize("ratios", [(4, 2), (4, 3)])
def test_melgan_torch_parity(ratios):
    """(4, 3) covers odd upsample ratios, where descript's transposed conv
    uses padding=r//2 + r%2 with output_padding=r%2 — several public MelGAN
    variants ship odd ratios, and the even-ratio formula silently
    mis-shifts them."""
    from speakingstyle_tpu.compat.torch_convert import convert_melgan
    from speakingstyle_tpu.models.melgan import MelGANGenerator

    torch.manual_seed(0)
    tgen = _torch_melgan(ratios=ratios).eval()
    sd = {k: v.detach().numpy() for k, v in tgen.state_dict().items()}
    params = convert_melgan(sd)

    gen = MelGANGenerator(n_mels=80, ngf=8, n_residual_layers=2, ratios=ratios)
    mel = np.random.default_rng(0).standard_normal((2, 13, 80)).astype(np.float32)
    wav_jax = np.asarray(gen.apply({"params": params}, jnp.asarray(mel)))
    with torch.no_grad():
        wav_torch = tgen(torch.from_numpy(mel).transpose(1, 2)).numpy()[:, 0]
    assert wav_jax.shape == wav_torch.shape
    np.testing.assert_allclose(wav_jax, wav_torch, atol=1e-5)


def test_melgan_get_vocoder_and_infer(tmp_path):
    """get_vocoder MelGAN branch: random init + vocoder_infer dispatch
    (log10 input scaling, ratio-product hop factor)."""
    import dataclasses

    from speakingstyle_tpu.configs.config import Config, ModelConfig, VocoderConfig
    from speakingstyle_tpu.models.melgan import MelGANGenerator
    from speakingstyle_tpu.synthesis import get_vocoder

    cfg = Config(model=ModelConfig(vocoder=VocoderConfig(model="MelGAN")))
    gen, params = get_vocoder(cfg)
    assert isinstance(gen, MelGANGenerator)
    mel = np.random.default_rng(0).standard_normal((1, 11, 80)).astype(np.float32)
    wavs = vocoder_infer(gen, params, jnp.asarray(mel), lengths=[8])
    assert wavs[0].dtype == np.int16
    assert len(wavs[0]) == 8 * int(np.prod(gen.ratios))
