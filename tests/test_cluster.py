"""Distributed control plane (tier-1).

Four layers, mirroring serving/cluster.py:
  1. lease table — epoch fencing and strict expiry against an explicit
     clock (no threads, no sockets, microsecond-fast);
  2. wire codec + idempotency — request/result round-trips and the
     replica-side duplicate-dispatch cache (no router);
  3. cluster e2e against in-process replica "processes" (a FakeProc
     wraps a real ReplicaServer + toy engine, so registration,
     heartbeats, dispatch, and chaos all cross real HTTP) — lease
     expiry mid-dispatch requeues without duplicating, partition heal
     re-admits through the breaker's half-open, a chaos process kill
     loses zero requests, and a slow primary is hedged to a second
     host;
  4. the surfaces other subsystems consume — quorum-gated readiness in
     /healthz stats and the autoscaler's scale floor.
"""

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from speakingstyle_tpu.configs.config import (
    AutoscaleConfig,
    ClusterConfig,
    Config,
    FleetConfig,
    ServeConfig,
)
from speakingstyle_tpu.faults import FaultPlan
from speakingstyle_tpu.obs import MetricsRegistry
from speakingstyle_tpu.serving.cluster import (
    ClusterRouter,
    LeaseTable,
    ReplicaServer,
    batch_key,
    decode_request,
    decode_result,
    encode_request,
    encode_result,
    _post_json,
)
from speakingstyle_tpu.serving.engine import SynthesisRequest
from speakingstyle_tpu.serving.fleet import FAILED, READY

# ---------------------------------------------------------------------------
# lease table (explicit clock, no threads)
# ---------------------------------------------------------------------------


def test_lease_heartbeat_exactly_at_expiry_renews():
    """Expiry is strict: a beat landing exactly ON the deadline still
    renews (now <= deadline); one tick past it does not."""
    t = LeaseTable(ttl_s=1.0)
    ok, epoch = t.register("r1", "127.0.0.1", 9999, 1, 42, now=100.0)
    assert ok and epoch == 1
    # exactly at the deadline: renewed, and the deadline slides forward
    assert t.heartbeat("r1", 1, True, now=101.0) == "renewed"
    lease = t.get("r1")
    assert lease.deadline == 102.0 and lease.ready
    # one tick past the (renewed) deadline: expired, lease untouched
    assert t.heartbeat("r1", 1, True, now=102.0 + 1e-9) == "expired"
    assert not t.alive("r1", now=102.0 + 1e-9)
    assert t.alive("r1", now=102.0)   # boundary is inclusive here too


def test_lease_epoch_fencing():
    """A registration or beat carrying an epoch older than the table's
    is rejected with the current epoch, so the caller can jump past it;
    an unknown replica's beat tells it to re-register."""
    t = LeaseTable(ttl_s=1.0)
    assert t.register("r1", "h", 1, 3, 0, now=0.0) == (True, 3)
    # stale re-register: rejected, answer carries the fencing epoch
    assert t.register("r1", "h", 1, 2, 0, now=0.5) == (False, 3)
    # stale beat from the zombie incarnation: fenced out
    assert t.heartbeat("r1", 2, True, now=0.5) == "stale"
    # the newer incarnation re-registers above the fence and lives on
    assert t.register("r1", "h", 1, 4, 0, now=0.5) == (True, 4)
    assert t.heartbeat("r1", 4, True, now=0.9) == "renewed"
    assert t.heartbeat("ghost", 1, True, now=0.9) == "unknown"
    t.drop("r1")
    assert t.heartbeat("r1", 4, True, now=1.0) == "unknown"


# ---------------------------------------------------------------------------
# wire codec + idempotency
# ---------------------------------------------------------------------------


def _req(i, L=8, T=4, **kw):
    return SynthesisRequest(
        id=f"q{i}", sequence=np.arange(1, L + 1, dtype=np.int32),
        ref_mel=np.random.default_rng(i).standard_normal(
            (T, 80)).astype(np.float32),
        **kw,
    )


def test_wire_codec_request_roundtrip():
    r = _req(0, p_control=1.25,
             d_control=np.linspace(0.5, 2.0, 8).astype(np.float32))
    d = encode_request(r)
    assert "arrival" not in d   # monotonic stamps do not transfer
    back = decode_request(d)
    assert back.id == r.id
    np.testing.assert_array_equal(back.sequence, r.sequence)
    np.testing.assert_array_equal(back.ref_mel, r.ref_mel)
    assert back.p_control == 1.25
    np.testing.assert_array_equal(back.d_control, r.d_control)
    # decoded arrays must be writable (pool staging slice-assigns)
    back.ref_mel[0, 0] = 7.0


def test_wire_codec_result_roundtrip_duck_typed():
    mel = np.random.default_rng(1).standard_normal((6, 80)).astype(
        np.float32)
    full = SimpleNamespace(id="a", mel=mel, mel_len=6, src_len=3,
                           bucket=SimpleNamespace(b=1, l_src=8, t_mel=16))
    sparse = SimpleNamespace(id="b")   # toy engines return bare objects
    out_full = decode_result(encode_result(full), served_by="h:1")
    out_sparse = decode_result(encode_result(sparse))
    np.testing.assert_array_equal(out_full.mel, mel)
    assert out_full.mel_len == 6 and out_full.served_by == "h:1"
    assert (out_full.bucket.b, out_full.bucket.l_src,
            out_full.bucket.t_mel) == (1, 8, 16)
    assert out_sparse.id == "b" and out_sparse.bucket is None
    assert out_sparse.mel.size == 0 and out_sparse.wav is None


def test_batch_key_stable_and_membership_sensitive():
    a = [_req(1), _req(2)]
    assert batch_key(a) == batch_key(list(a))
    assert batch_key(a) != batch_key([_req(1)])       # different membership
    assert batch_key(a) != batch_key([_req(2), _req(1)])  # different order
    assert len(batch_key(a)) == 32


class _CountingEngine:
    is_ready = True

    def __init__(self, stall_s=0.0, stall_ids=()):
        self.runs = []
        self.stall_s = stall_s
        self.stall_ids = set(stall_ids)
        self.unstall = threading.Event()
        self._lock = threading.Lock()

    def precompile(self):
        return 0.0

    def run(self, requests):
        if any(r.id in self.stall_ids for r in requests):
            self.unstall.wait(timeout=self.stall_s)
        with self._lock:
            self.runs.extend(r.id for r in requests)
        return [SimpleNamespace(id=r.id, mel_len=1) for r in requests]


def test_idempotency_cache_dedupes_and_evicts():
    """Check-then-run-then-store is atomic: the duplicate leg of a
    hedge is a cache lookup, never a second lattice run — and the cache
    is bounded (LRU) so it can never grow with traffic (JL012)."""
    eng = _CountingEngine()
    srv = ReplicaServer(eng, "r1", "127.0.0.1:9", ClusterConfig(
        idempotency_cache=2))
    try:
        body = {"key": "k1", "requests": [encode_request(_req(1))]}
        code, first = srv._handle_dispatch(body)
        assert code == 200 and first["idempotent"] is False
        code, dup = srv._handle_dispatch(body)
        assert code == 200 and dup["idempotent"] is True
        assert dup["results"][0]["id"] == "q1"
        assert eng.runs == ["q1"]   # exactly one real run
        assert srv._idem_hits.value == 1
        # two more distinct keys evict k1 from the 2-entry cache
        for k, i in (("k2", 2), ("k3", 3)):
            srv._handle_dispatch(
                {"key": k, "requests": [encode_request(_req(i))]})
        assert srv._idem_evict.value == 1
        code, rerun = srv._handle_dispatch(body)
        assert rerun["idempotent"] is False   # evicted: genuinely re-ran
        assert eng.runs.count("q1") == 2
    finally:
        srv._httpd.server_close()


def test_idempotency_duplicate_leg_parks_during_execution():
    """A hedge leg arriving WHILE the first leg is still running its
    batch must park on the in-flight claim and answer from the cache —
    never a second lattice run, and never while holding the dispatch
    lock across engine.run (the witness-visible lock-order hazard the
    in-flight protocol exists to avoid)."""
    eng = _CountingEngine(stall_s=5.0, stall_ids=("q1",))
    srv = ReplicaServer(eng, "r1", "127.0.0.1:9", ClusterConfig())
    try:
        body = {"key": "k1", "requests": [encode_request(_req(1))]}
        out = {}

        def first_leg():
            out["first"] = srv._handle_dispatch(body)

        t = threading.Thread(target=first_leg, daemon=True)
        t.start()
        assert _wait(lambda: "k1" in srv._inflight, 2.0)
        # duplicate leg fires mid-execution, then the stall releases
        def second_leg():
            out["dup"] = srv._handle_dispatch(body)

        t2 = threading.Thread(target=second_leg, daemon=True)
        t2.start()
        time.sleep(0.05)
        eng.unstall.set()
        t.join(timeout=5)
        t2.join(timeout=5)
        assert out["first"][1]["idempotent"] is False
        assert out["dup"][1]["idempotent"] is True
        assert eng.runs == ["q1"]   # exactly one real run
        assert srv._inflight == {}  # claim cleared
    finally:
        srv._httpd.server_close()


# ---------------------------------------------------------------------------
# cluster e2e — in-process replica "processes" over real HTTP
# ---------------------------------------------------------------------------


class _FakeProc:
    """One replica process, in-process: a real ReplicaServer (its own
    HTTP socket, registration, heartbeat thread) behind the subprocess
    surface ``_acquire_replica``/``_retire_process`` drive."""

    def __init__(self, rid, router_addr, ccfg, engine=None):
        self.engine = engine if engine is not None else _CountingEngine()
        self.server = ReplicaServer(self.engine, rid, router_addr, ccfg)
        self._rc = None
        self.server.start()

    def poll(self):
        return self._rc

    def terminate(self):
        self._rc = 0
        self.engine.unstall.set()
        self.server.close()

    kill = terminate

    def wait(self, timeout=None):
        return self._rc


def _cfg(**cluster_kw):
    ckw = dict(enabled=True, heartbeat_interval_s=0.1, lease_miss_budget=3,
               spawn_grace_s=10.0, quorum=1, hedge_quantile=0.0)
    ckw.update(cluster_kw)
    return Config(serve=ServeConfig(
        batch_buckets=[1], src_buckets=[16], mel_buckets=[64],
        frames_per_phoneme=2, max_wait_ms=5.0,
        fleet=FleetConfig(
            queue_depth=64, stream_window=8,
            rewarm_backoff_s=0.05, rewarm_backoff_max_s=0.5,
            class_deadline_ms={"interactive": 10_000.0,
                               "batch": 20_000.0},
        ),
        cluster=ClusterConfig(**ckw),
    ))


def _make_cluster(replicas, engine_factory=None, **cluster_kw):
    cfg = _cfg(**cluster_kw)
    procs = {}

    def spawn(rid, router_addr, extra):
        eng = engine_factory(rid) if engine_factory is not None else None
        p = _FakeProc(rid, router_addr, cfg.serve.cluster, engine=eng)
        procs[rid] = p
        return p

    reg = MetricsRegistry()
    router = ClusterRouter(spawn, cfg, replicas=replicas, registry=reg,
                           fault_plan=FaultPlan())
    return router, procs, reg


def _ready_count(router):
    return sum(s == READY for s in router.states().values())


def _wait(pred, timeout=20.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def test_cluster_dispatch_quorum_and_stale_register():
    """Happy path: dispatches cross the wire with served_by stamped;
    ready() is quorum-gated; a stale-epoch registration is 409ed with
    the fencing epoch (the wire half of the epoch fence)."""
    router, procs, reg = _make_cluster(replicas=1, quorum=2)
    try:
        assert router.wait_ready(timeout=20, n=1)
        # one READY replica under quorum=2: NOT ready (healthz 503)
        assert router.ready() is False
        router.scale_to(2)
        assert router.wait_ready(timeout=20, n=2)
        assert router.ready() is True
        futs = [router.submit(_req(i)) for i in range(4)]
        served = {f.result(timeout=10).served_by for f in futs}
        assert all(served)
        rows = router.cluster_stats()
        assert len(rows) == 2
        for row in rows:
            assert row["ready"] and not row["expired"]
            assert "lease_age_s" in row and "last_heartbeat_s" in row
        # stale epoch over the wire: 409 + the epoch to register above
        host, _, port = router.control_addr.rpartition(":")
        rid = rows[0]["replica_id"]
        code, body = _post_json(host, int(port), "/register", {
            "replica_id": rid, "host": "127.0.0.1", "port": 1,
            "epoch": 0, "pid": 0,
        }, timeout=2.0)
        assert code == 409 and body["epoch"] >= 1
    finally:
        router.close()
    assert all(p.poll() is not None for p in procs.values())


def test_lease_expiry_mid_dispatch_requeues_not_duplicates():
    """A lease expiring under an in-flight dispatch steals the batch
    (hang-watchdog style) and requeues it at its original deadline; the
    stalled replica's late result fails its claim and is discarded, so
    the client sees exactly one result — from the OTHER replica."""
    once = {"armed": True}
    arm_lock = threading.Lock()

    class _StallFirst(_CountingEngine):
        # only the FIRST engine to see q100 stalls: the requeued batch
        # must run clean on the survivor
        def run(self, requests):
            if any(r.id == "q100" for r in requests):
                with arm_lock:
                    hit = once["armed"]
                    once["armed"] = False
                if hit:
                    self.unstall.wait(timeout=30.0)
            return super().run(requests)

    engines = {}

    def factory(rid):
        engines[rid] = _StallFirst()
        return engines[rid]

    router, procs, reg = _make_cluster(replicas=2, engine_factory=factory)
    try:
        assert router.wait_ready(timeout=20, n=2)
        fut = router.submit(_req(100))
        # find which replica holds q100 in flight, then partition it so
        # its heartbeats stop renewing and the lease ages out (TTL =
        # 0.1s * (3 + 1) = 0.4s)
        assert _wait(lambda: any(r.inflight for r in router._replicas),
                     timeout=5)
        stalled = None
        for rep in router._replicas:
            if rep.inflight:
                stalled = rep.engine.replica_id
        assert stalled is not None
        stalled_addr = f"{procs[stalled].server.host}:" \
                       f"{procs[stalled].server.port}"
        router.partition(stalled)
        # the sweeper expires the lease and requeues; the survivor runs
        # the batch and completes the future
        result = fut.result(timeout=20)
        assert result.served_by != stalled_addr
        assert reg.value("serve_lease_expired_total") == 1
        assert reg.histogram("serve_lease_requeue_seconds").count >= 1
        # release the zombie leg: its late claim must be discarded, not
        # doubled into the (already resolved) future
        procs[stalled].engine.unstall.set()
        time.sleep(0.3)
        assert fut.result(timeout=1).served_by != stalled_addr
        survivors = [e for r, e in engines.items() if r != stalled]
        assert sum(e.runs.count("q100") for e in survivors) == 1
    finally:
        for p in procs.values():
            p.engine.unstall.set()
        router.close()


def test_partition_heal_readmits_same_process_via_half_open():
    """A partitioned replica fails (lease expiry -> breaker) and its
    still-live process is stashed as an orphan; healing the partition
    lets the next half-open re-warm ADOPT that process instead of
    spawning — same pid, bumped epoch."""
    router, procs, reg = _make_cluster(replicas=2, quorum=2)
    try:
        assert router.wait_ready(timeout=20, n=2)
        target = router._replicas[0].engine.replica_id
        epoch_before = router.leases.get(target).epoch
        router.partition(target)
        assert _wait(lambda: FAILED in router.states().values(),
                     timeout=20)
        assert router.ready() is False   # below quorum while failed
        spawned_before = len(procs)
        router.heal(target)
        assert _wait(lambda: _ready_count(router) >= 2, timeout=20)
        assert router.ready() is True
        # adopted, not respawned: no new process, epoch moved past the
        # partition-era lease
        assert len(procs) == spawned_before
        assert router.leases.get(target).epoch > epoch_before
        futs = [router.submit(_req(200 + i)) for i in range(3)]
        assert all(f.result(timeout=10).served_by for f in futs)
    finally:
        router.close()


def test_chaos_proc_kill_loses_zero_requests():
    """The replica_proc_kill chaos fault kills a real process
    mid-dispatch; every submitted request still completes (requeue +
    respawn), and the fleet returns to full READY strength."""
    router, procs, reg = _make_cluster(replicas=2, quorum=2)
    try:
        assert router.wait_ready(timeout=20, n=2)
        for f in [router.submit(_req(i)) for i in range(4)]:
            f.result(timeout=10)
        router.fault_plan.arm("replica_proc_kill",
                              router.dispatch_total + 1)
        futs = [router.submit(_req(100 + i)) for i in range(8)]
        for f in futs:
            assert f.result(timeout=30).served_by   # zero lost
        assert sum(p.poll() is not None for p in procs.values()) == 1
        assert _wait(lambda: _ready_count(router) >= 2, timeout=20)
        assert len(procs) == 3   # the kill forced one real respawn
    finally:
        router.close()


def test_hedge_fires_on_slow_primary_and_second_host_wins():
    """A slow (not failed) first leg hedges to a different host after
    the class's hedge delay; the hedge wins, the client result carries
    the second host, and both hedge counters account for it."""
    stall_once = {"armed": True}
    lock = threading.Lock()

    class _SlowOnce(_CountingEngine):
        def run(self, requests):
            if any(r.id == "q500" for r in requests):
                with lock:
                    hit = stall_once["armed"]
                    stall_once["armed"] = False
                if hit:
                    self.unstall.wait(timeout=5.0)
            with self._lock:
                self.runs.extend(r.id for r in requests)
            return [SimpleNamespace(id=r.id, mel_len=1)
                    for r in requests]

    engines = {}

    def factory(rid):
        engines[rid] = _SlowOnce()
        return engines[rid]

    router, procs, reg = _make_cluster(
        replicas=2, engine_factory=factory,
        hedge_quantile=0.95, hedge_min_ms=50.0, hedge_max_ms=150.0,
    )
    try:
        assert router.wait_ready(timeout=20, n=2)
        fut = router.submit(SynthesisRequest(
            id="q500", sequence=np.ones(8, np.int32),
            ref_mel=np.zeros((4, 80), np.float32)))
        result = fut.result(timeout=10)
        assert result.served_by
        assert reg.value("serve_hedge_fired_total",
                         {"class": "interactive"}) == 1
        assert reg.value("serve_hedge_won_total",
                         {"class": "interactive"}) == 1
    finally:
        for p in procs.values():
            p.engine.unstall.set()
        router.close()


# ---------------------------------------------------------------------------
# consuming surfaces: healthz aggregation + autoscaler floor
# ---------------------------------------------------------------------------


def test_server_stats_aggregates_cluster_block():
    from speakingstyle_tpu.serving.server import SynthesisServer

    router, procs, reg = _make_cluster(replicas=1, quorum=1)
    server = None
    try:
        assert router.wait_ready(timeout=20, n=1)
        server = SynthesisServer(router=router, host="127.0.0.1", port=0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        stats = server.stats()
        assert stats["ready"] is True
        cluster = stats["cluster"]
        assert cluster["quorum"] == 1
        assert cluster["control_addr"] == router.control_addr
        row = cluster["replicas"][0]
        assert row["ready"] and not row["partitioned"]
        assert ":" in row["host"]
    finally:
        if server is not None:
            server.shutdown()
        else:
            router.close()


def test_autoscaler_respects_cluster_scale_floor():
    """A ClusterRouter publishes its quorum as scale_floor; the
    autoscaler treats it as a hard floor — an under-quorum fleet is
    corrected up immediately, and calm never drains below it."""
    from speakingstyle_tpu.serving.autoscale import Autoscaler

    calls = []
    fake = SimpleNamespace(
        registry=MetricsRegistry(), events=None,
        fleet=SimpleNamespace(queue_depth=64),
        scale_floor=2, rollout_active=False,
        live_replica_count=lambda: 1,
        pending_depth=lambda: 0, occupancy=lambda: 0.0,
        warmup_cost_s=lambda: None,
        scale_to=lambda n: calls.append(n),
    )
    acfg = AutoscaleConfig(enabled=True, min_replicas=1, max_replicas=4)
    a = Autoscaler(fake, acfg, start=False)
    assert a.step(now=100.0) == "min_bound"
    assert calls == [2]
    # at the floor, a long calm window never drains below it
    fake.live_replica_count = lambda: 2
    for t in range(200, 2000, 100):
        assert a.step(now=float(t)) is None
    assert calls == [2]


def test_remote_engine_surface_matches_router_contract():
    """The RemoteReplica interface rollout/autoscale drive: no vocoder
    (streaming stays in-process), compile_count via /healthz, is_ready
    tied to the lease."""
    router, procs, reg = _make_cluster(replicas=1, quorum=1)
    try:
        assert router.wait_ready(timeout=20, n=1)
        eng = router._replicas[0].engine
        assert eng.vocoder is None
        assert eng.is_ready is True
        assert eng.compile_count == 0   # toy engine: nothing compiled
        router.partition(eng.replica_id)
        assert _wait(lambda: not eng.is_ready, timeout=5)
    finally:
        router.close()
