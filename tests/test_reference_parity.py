"""Numerical parity vs the reference PyTorch FastSpeech2 (the BASELINE.md
quality gate).

Builds the REFERENCE model (imported from /root/reference, torch CPU) at
BC2013 dims with random weights, runs a teacher-forced forward on a fixed
batch, converts its state_dict through compat.torch_convert.convert_fastspeech2,
runs OUR model on the same batch, and asserts mel / postnet-mel / pitch /
energy / log-duration agreement (fp32, atol ~1e-4).

Reference under test: model/fastspeech2.py:44-120, model/modules.py,
transformer/{Models,Layers,SubLayers,Modules}.py. Mirrors the approach of
tests/test_hifigan.py (elementwise generator parity).
"""

import contextlib
import dataclasses
import io
import json
import os
import sys

import numpy as np
import pytest

REF_DIR = "/root/reference"

torch = pytest.importorskip("torch")
yaml = pytest.importorskip("yaml")

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(
        not os.path.isdir(os.path.join(REF_DIR, "model")),
        reason="reference checkout not available",
    ),
]

# Fixed batch geometry: unequal lengths to exercise masking.
B, L_SRC, T_MEL = 2, 8, 16
SRC_LENS = [8, 6]
MEL_LENS = [16, 12]
DURATIONS = [
    [2, 2, 2, 2, 2, 2, 2, 2],      # sums to 16
    [3, 2, 2, 2, 2, 1, 0, 0],      # sums to 12, zeros on padding
]
N_MELS = 80
STATS = {"pitch": [-2.5, 9.0, 0.0, 1.0], "energy": [-1.5, 8.0, 0.0, 1.0]}


def _fixed_batch():
    rng = np.random.default_rng(1234)
    texts = rng.integers(1, 360, (B, L_SRC)).astype(np.int64)
    texts[1, SRC_LENS[1]:] = 0
    mels = rng.standard_normal((B, T_MEL, N_MELS)).astype(np.float32)
    mels[1, MEL_LENS[1]:] = 0.0
    pitches = rng.uniform(-2.0, 8.0, (B, L_SRC)).astype(np.float32)
    energies = rng.uniform(-1.0, 7.0, (B, L_SRC)).astype(np.float32)
    pitches[1, SRC_LENS[1]:] = 0.0
    energies[1, SRC_LENS[1]:] = 0.0
    return texts, mels, pitches, energies


@pytest.fixture(scope="module")
def reference_run(tmp_path_factory):
    """(state_dict_numpy, outputs_numpy) from the reference torch model."""
    stats_dir = tmp_path_factory.mktemp("ref_stats")
    (stats_dir / "stats.json").write_text(json.dumps(STATS))

    # The reference's transformer/Models.py imports text.symbols, whose
    # package __init__ drags in unidecode/inflect (not installed here).
    # Neither is used at model-build or forward time — stub them.
    import types

    sys.modules.setdefault(
        "unidecode", types.SimpleNamespace(unidecode=lambda s: s)
    )
    sys.modules.setdefault(
        "inflect", types.SimpleNamespace(engine=lambda: None)
    )
    sys.path.insert(0, REF_DIR)
    try:
        from model.fastspeech2 import FastSpeech2 as RefFastSpeech2
    finally:
        sys.path.remove(REF_DIR)

    with open(os.path.join(REF_DIR, "config/BC2013/preprocess.yaml")) as f:
        pc = yaml.safe_load(f)
    with open(os.path.join(REF_DIR, "config/BC2013/model.yaml")) as f:
        mc = yaml.safe_load(f)
    pc["path"]["preprocessed_path"] = str(stats_dir)

    torch.manual_seed(0)
    ref_model = RefFastSpeech2(pc, mc).eval()

    texts, mels, pitches, energies = _fixed_batch()
    with torch.no_grad(), contextlib.redirect_stdout(io.StringIO()):
        out = ref_model(
            speakers=torch.zeros(B, dtype=torch.long),
            texts=torch.from_numpy(texts),
            src_lens=torch.tensor(SRC_LENS),
            max_src_len=L_SRC,
            mels=torch.from_numpy(mels),
            mel_lens=torch.tensor(MEL_LENS),
            max_mel_len=T_MEL,
            p_targets=torch.from_numpy(pitches),
            e_targets=torch.from_numpy(energies),
            d_targets=torch.tensor(DURATIONS),
        )
    (mel, postnet_mel, p_pred, e_pred, log_d_pred, d_rounded,
     src_masks, mel_masks, src_lens, mel_lens) = out

    # Free-running pass: same style mel, NO p/e/d targets — the synthesis
    # path (reference: model/modules.py:137-144 predicted durations).
    with torch.no_grad(), contextlib.redirect_stdout(io.StringIO()):
        fr = ref_model(
            speakers=torch.zeros(B, dtype=torch.long),
            texts=torch.from_numpy(texts),
            src_lens=torch.tensor(SRC_LENS),
            max_src_len=L_SRC,
            mels=torch.from_numpy(mels),
            mel_lens=torch.tensor(MEL_LENS),
            max_mel_len=T_MEL,
        )
    (fr_mel, fr_postnet, fr_p, fr_e, fr_logd, fr_d_rounded,
     _, _, _, fr_mel_lens) = fr

    sd = {k: v.detach().cpu().numpy() for k, v in ref_model.state_dict().items()}
    outputs = {
        "mel": mel.numpy(),
        "mel_postnet": postnet_mel.numpy(),
        "pitch_prediction": p_pred.numpy(),
        "energy_prediction": e_pred.numpy(),
        "log_duration_prediction": log_d_pred.numpy(),
        "fr_mel": fr_mel.numpy(),
        "fr_mel_postnet": fr_postnet.numpy(),
        "fr_durations": fr_d_rounded.numpy(),
        "fr_mel_lens": fr_mel_lens.numpy(),
        "fr_log_duration_prediction": fr_logd.numpy(),
    }
    return sd, outputs, str(stats_dir)


def _our_config(stats_dir: str):
    from speakingstyle_tpu.configs.config import load_config

    cfg = load_config(preset="BC2013")
    return dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, compute_dtype="float32"),
        preprocess=dataclasses.replace(
            cfg.preprocess,
            path=dataclasses.replace(
                cfg.preprocess.path, preprocessed_path=stats_dir
            ),
        ),
    )


def test_fastspeech2_numerical_parity(reference_run):
    import jax.numpy as jnp

    from speakingstyle_tpu.compat.torch_convert import convert_fastspeech2
    from speakingstyle_tpu.models.factory import build_model

    sd, ref_out, stats_dir = reference_run
    converted = convert_fastspeech2(sd)
    cfg = _our_config(stats_dir)
    model = build_model(cfg)

    texts, mels, pitches, energies = _fixed_batch()
    out = model.apply(
        {"params": converted["params"], "batch_stats": converted["batch_stats"]},
        speakers=jnp.zeros((B,), jnp.int32),
        texts=jnp.asarray(texts, jnp.int32),
        src_lens=jnp.asarray(SRC_LENS, jnp.int32),
        mels=jnp.asarray(mels),
        mel_lens=jnp.asarray(MEL_LENS, jnp.int32),
        max_mel_len=T_MEL,
        p_targets=jnp.asarray(pitches),
        e_targets=jnp.asarray(energies),
        d_targets=jnp.asarray(DURATIONS, jnp.int32),
        deterministic=True,
    )

    src_valid = np.arange(L_SRC)[None, :] < np.asarray(SRC_LENS)[:, None]
    mel_valid = np.arange(T_MEL)[None, :] < np.asarray(MEL_LENS)[:, None]

    for key, valid in [
        ("pitch_prediction", src_valid),
        ("energy_prediction", src_valid),
        ("log_duration_prediction", src_valid),
        ("mel", mel_valid[..., None]),
        ("mel_postnet", mel_valid[..., None]),
    ]:
        got = np.asarray(out[key], np.float32)
        want = ref_out[key]
        got, want = np.broadcast_arrays(got * valid, want * valid)
        err = np.abs(got - want).max()
        assert err < 2e-4, f"{key}: max abs err {err}"


def test_fastspeech2_free_running_parity(reference_run):
    """The SYNTHESIS path: no targets — predicted durations
    round(exp(logd)-1)*control (ops/length_regulator.py:51-61) and the
    rebuilt mel mask must agree with the reference's inference branch
    (model/modules.py:137-144), and the mels must match on the predicted
    valid region. This is exactly what ships to users via `synthesize`."""
    import jax.numpy as jnp

    from speakingstyle_tpu.compat.torch_convert import convert_fastspeech2
    from speakingstyle_tpu.models.factory import build_model

    sd, ref_out, stats_dir = reference_run
    converted = convert_fastspeech2(sd)
    cfg = _our_config(stats_dir)
    model = build_model(cfg)

    texts, mels, pitches, energies = _fixed_batch()
    MAX_MEL = 96  # static bound; must exceed every predicted length
    out = model.apply(
        {"params": converted["params"], "batch_stats": converted["batch_stats"]},
        speakers=jnp.zeros((B,), jnp.int32),
        texts=jnp.asarray(texts, jnp.int32),
        src_lens=jnp.asarray(SRC_LENS, jnp.int32),
        mels=jnp.asarray(mels),
        mel_lens=jnp.asarray(MEL_LENS, jnp.int32),
        max_mel_len=MAX_MEL,
        deterministic=True,
    )

    ref_d = ref_out["fr_durations"]
    ref_lens = ref_out["fr_mel_lens"].astype(np.int64)
    src_valid = np.arange(L_SRC)[None, :] < np.asarray(SRC_LENS)[:, None]

    # the predicted lengths must stay inside the static bound, or the
    # comparison below silently truncates
    assert ref_lens.max() < MAX_MEL and ref_lens.max() > 0

    got_logd = np.asarray(out["log_duration_prediction"]) * src_valid
    want_logd = ref_out["fr_log_duration_prediction"] * src_valid
    np.testing.assert_allclose(got_logd, want_logd, atol=2e-4)

    # durations: integer agreement, not approximate — one frame off shifts
    # every downstream frame
    np.testing.assert_array_equal(
        np.asarray(out["durations"]) * src_valid,
        ref_d.astype(np.int64) * src_valid,
    )
    np.testing.assert_array_equal(np.asarray(out["mel_lens"]), ref_lens)

    T_ref = ref_out["fr_mel"].shape[1]
    mel_valid = (np.arange(T_ref)[None, :] < ref_lens[:, None])[..., None]
    for key in ("mel", "mel_postnet"):
        got = np.asarray(out[key], np.float32)[:, :T_ref]
        want = ref_out[f"fr_{key}"]
        got, want = np.broadcast_arrays(got * mel_valid, want * mel_valid)
        err = np.abs(got - want).max()
        assert err < 5e-4, f"free-running {key}: max abs err {err}"
