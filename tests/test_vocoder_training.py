"""HiFi-GAN discriminators, GAN losses, and the vocoder training loop."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from speakingstyle_tpu.configs.config import Config
from speakingstyle_tpu.models.hifigan_disc import (
    MultiPeriodDiscriminator,
    MultiScaleDiscriminator,
    _avg_pool1d,
    discriminator_loss,
    feature_matching_loss,
    generator_adversarial_loss,
)

SEG = 1024  # short segments keep CPU tests fast

# Small generator topology for the GAN-LOOP tests: upsample product still
# 256 (= the mel hop, so wav/mel lengths stay consistent) but 16x fewer
# channels than the default 512-ch topology. GAN-loop math is
# topology-independent; full-topology coverage: the GENERATOR in
# test_hifigan's torch-parity tests and the committed on-TPU descent
# artifact (artifacts/vocoder_descent_r5), the DISCRIMINATORS in
# test_default_discriminator_topology. Cut the CPU suite by minutes.
SMALL_GEN = dict(
    upsample_rates=(8, 8, 2, 2),
    upsample_kernel_sizes=(16, 16, 4, 4),
    upsample_initial_channel=32,
)


def _small_discs():
    """2-period MPD + 2-scale MSD for the loop tests (same loss math over
    a shorter list; the default 5-period/3-scale topology is covered by
    test_default_discriminator_topology below)."""
    return dict(
        mpd=MultiPeriodDiscriminator(periods=(2, 3)),
        msd=MultiScaleDiscriminator(n_scales=2),
    )


@pytest.mark.slow
def test_default_discriminator_topology():
    """The reference topology (5 periods incl. the prime-11 padding path,
    3 scales incl. the twice-pooled one) forwards with the right number
    of score/feature outputs — the loop tests use smaller discriminators,
    so this is the full-topology gate."""
    y = jnp.asarray(
        np.random.default_rng(0).standard_normal((1, SEG)), jnp.float32
    )
    mpd = MultiPeriodDiscriminator()
    pr, pg, fr, fg = mpd.apply(mpd.init(jax.random.PRNGKey(0), y, y), y, y)
    assert len(pr) == len(pg) == len(fr) == len(fg) == 5
    msd = MultiScaleDiscriminator()
    variables = msd.init(jax.random.PRNGKey(0), y, y)
    (sr, sg, fr2, fg2), _ = msd.apply(
        variables, y, y, update_stats=True, mutable=["batch_stats"]
    )
    assert len(sr) == len(sg) == len(fr2) == len(fg2) == 3
    for t in (*pr, *sr):
        assert np.isfinite(np.asarray(t)).all()
SMALL_GEN_JSON = dict(
    SMALL_GEN,
    resblock="1",
    resblock_kernel_sizes=(3, 7, 11),
    resblock_dilation_sizes=((1, 3, 5), (1, 3, 5), (1, 3, 5)),
)


@pytest.mark.slow
def test_period_discriminator_shapes():
    mpd = MultiPeriodDiscriminator(periods=(2, 3))
    y = jnp.asarray(np.random.default_rng(0).standard_normal((2, SEG)), jnp.float32)
    params = mpd.init(jax.random.PRNGKey(0), y, y)["params"]
    outs_r, outs_g, fmaps_r, fmaps_g = mpd.apply({"params": params}, y, y)
    assert len(outs_r) == 2 and len(fmaps_r) == 2
    assert all(len(f) == 6 for f in fmaps_r)  # 5 conv + post
    # identical inputs -> identical outputs
    for a, b in zip(outs_r, outs_g):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_scale_discriminator_shapes():
    msd = MultiScaleDiscriminator(n_scales=2)
    y = jnp.asarray(np.random.default_rng(0).standard_normal((2, SEG)), jnp.float32)
    variables = msd.init(jax.random.PRNGKey(0), y, y)
    outs_r, _, fmaps_r, _ = msd.apply(variables, y, y)
    assert len(outs_r) == 2
    assert all(len(f) == 8 for f in fmaps_r)  # 7 conv + post


def test_avg_pool_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.random.default_rng(0).standard_normal((2, 64)).astype(np.float32)
    ours = np.asarray(_avg_pool1d(jnp.asarray(x)))
    theirs = torch.nn.functional.avg_pool1d(
        torch.from_numpy(x)[:, None], 4, 2, padding=2
    )[:, 0].numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-6)


def test_gan_losses():
    real = [jnp.ones((2, 10))]
    fake = [jnp.zeros((2, 10))]
    # perfect discriminator: D(y)=1, D(y_hat)=0 -> loss 0
    assert float(discriminator_loss(real, fake)) == pytest.approx(0.0)
    # perfectly fooled: D(y_hat)=1 -> generator loss 0
    assert float(generator_adversarial_loss(real)) == pytest.approx(0.0)
    assert float(generator_adversarial_loss(fake)) == pytest.approx(10.0 * 0 + 1.0)
    fm = feature_matching_loss([[jnp.ones((2, 4))]], [[jnp.zeros((2, 4))]])
    assert float(fm) == pytest.approx(2.0)


def test_differentiable_mel_matches_numpy():
    from speakingstyle_tpu.audio.mel import mel_filterbank
    from speakingstyle_tpu.audio.stft import hann_window
    from speakingstyle_tpu.data.preprocessor import _numpy_mel_energy
    from speakingstyle_tpu.training.vocoder_trainer import differentiable_mel

    cfg = Config()
    pp = cfg.preprocess.preprocessing
    rng = np.random.default_rng(0)
    # bounded like real audio: _numpy_mel_energy clips to [-1, 1], the
    # differentiable path (tanh generator output) never needs to
    wav = np.clip(rng.standard_normal(SEG).astype(np.float32) * 0.3, -1, 1)
    mel_jax = np.asarray(differentiable_mel(cfg)(jnp.asarray(wav)[None]))[0]
    fb = mel_filterbank(pp.audio.sampling_rate, pp.stft.filter_length, 80,
                        pp.mel.mel_fmin, pp.mel.mel_fmax)
    win = hann_window(pp.stft.win_length, pp.stft.filter_length)
    mel_np, _ = _numpy_mel_energy(wav, fb, win, pp.stft.filter_length,
                                  pp.stft.hop_length)
    T = min(mel_jax.shape[0], mel_np.shape[0])
    np.testing.assert_allclose(mel_jax[:T], mel_np[:T], atol=2e-4)


def test_mel_wav_dataset(tmp_path):
    import scipy.io.wavfile

    from speakingstyle_tpu.data.mel_dataset import MelWavDataset, scan_wavs

    rng = np.random.default_rng(0)
    for i in range(4):
        w = (rng.standard_normal(6000) * 8000).astype(np.int16)
        scipy.io.wavfile.write(tmp_path / f"u{i}.wav", 22050, w)
    paths = scan_wavs(str(tmp_path))
    assert len(paths) == 4
    ds = MelWavDataset(paths, Config(), segment_size=SEG, batch_size=2)
    wavs, mels = next(ds.epoch(shuffle=False))
    assert wavs.shape == (2, SEG)
    assert mels.shape == (2, SEG // 256, 80)


@pytest.mark.slow
def test_vocoder_train_step_decreases_mel_l1(tmp_path):
    """A few GAN steps run end-to-end and produce finite, improving losses."""
    import scipy.io.wavfile

    from speakingstyle_tpu.data.mel_dataset import MelWavDataset
    from speakingstyle_tpu.training.vocoder_trainer import (
        VocoderHParams,
        init_vocoder_state,
        make_vocoder_train_step,
        restore_vocoder,
        save_vocoder,
    )

    cfg = Config()
    hp = VocoderHParams(segment_size=SEG, learning_rate=5e-4)
    rng = np.random.default_rng(0)
    t = np.arange(SEG * 4) / 22050
    wav = (0.5 * np.sin(2 * np.pi * 220 * t) * 30000).astype(np.int16)
    scipy.io.wavfile.write(tmp_path / "a.wav", 22050, wav)

    from speakingstyle_tpu.models.hifigan import Generator

    state, gen, mpd, msd, gen_tx, disc_tx = init_vocoder_state(
        cfg, hp, jax.random.PRNGKey(0), gen=Generator(**SMALL_GEN),
        **_small_discs(),
    )
    step = make_vocoder_train_step(cfg, hp, gen, mpd, msd, gen_tx, disc_tx)
    ds = MelWavDataset([str(tmp_path / "a.wav")], cfg, segment_size=SEG,
                       batch_size=1)
    wavs, mels = next(ds.epoch(shuffle=False))
    first = None
    for i in range(4):
        state, metrics = step(state, jnp.asarray(wavs), jnp.asarray(mels))
        vals = {k: float(v) for k, v in metrics.items()}
        assert all(np.isfinite(v) for v in vals.values()), vals
        if first is None:
            first = vals
    assert vals["mel_l1"] < first["mel_l1"]
    assert int(state.step) == 4

    # checkpoint round-trip + generator export loads in get_vocoder
    gen_path = save_vocoder(str(tmp_path / "ckpt" / "v.msgpack"), state)
    state2, *_ = init_vocoder_state(
        cfg, hp, jax.random.PRNGKey(1), gen=Generator(**SMALL_GEN),
        **_small_discs(),
    )
    state2 = restore_vocoder(str(tmp_path / "ckpt" / "v.msgpack"), state2)
    assert int(state2.step) == 4
    import json as _json

    from speakingstyle_tpu.synthesis import get_vocoder

    cfg_json = tmp_path / "config.json"
    cfg_json.write_text(_json.dumps(SMALL_GEN_JSON))
    gen2, params2 = get_vocoder(cfg, gen_path, config_path=str(cfg_json))
    leaves1 = jax.tree_util.tree_leaves(state.gen_params)
    leaves2 = jax.tree_util.tree_leaves(params2)
    np.testing.assert_allclose(np.asarray(leaves1[0]), np.asarray(leaves2[0]))


@pytest.mark.slow
def test_vocoder_train_step_sharded():
    """The GAN step compiles and runs over an 8-device data mesh."""
    from speakingstyle_tpu.parallel.mesh import make_mesh
    from speakingstyle_tpu.training.vocoder_trainer import (
        VocoderHParams,
        init_vocoder_state,
        make_vocoder_train_step,
    )

    from speakingstyle_tpu.models.hifigan import Generator

    cfg = Config()
    hp = VocoderHParams(segment_size=SEG)
    mesh = make_mesh(data=8, model=1)
    state, gen, mpd, msd, gen_tx, disc_tx = init_vocoder_state(
        cfg, hp, jax.random.PRNGKey(0), gen=Generator(**SMALL_GEN),
        **_small_discs(),
    )
    step = make_vocoder_train_step(cfg, hp, gen, mpd, msd, gen_tx, disc_tx,
                                   mesh=mesh)
    rng = np.random.default_rng(0)
    wavs = jnp.asarray(rng.standard_normal((8, SEG)), jnp.float32) * 0.1
    mels = jnp.asarray(rng.standard_normal((8, SEG // 256, 80)), jnp.float32)
    state, metrics = step(state, wavs, mels)
    assert np.isfinite(float(metrics["gen_loss"]))


@pytest.mark.slow
def test_vocoder_optimizer_torch_adamw_weight_decay():
    """The GAN optimizers must use torch AdamW's default weight decay (0.01),
    not optax.adamw's 1e-4 (regression: silent recipe divergence). With zero
    gradients the AdamW update reduces to -lr * wd * param."""
    from speakingstyle_tpu.training.vocoder_trainer import (
        VocoderHParams,
        init_vocoder_state,
    )

    from speakingstyle_tpu.models.hifigan import Generator

    cfg = Config()
    hp = VocoderHParams(segment_size=SEG)
    state, gen, mpd, msd, gen_tx, disc_tx = init_vocoder_state(
        cfg, hp, jax.random.PRNGKey(0), gen=Generator(**SMALL_GEN),
        **_small_discs(),
    )
    zero_grads = jax.tree_util.tree_map(jnp.zeros_like, state.gen_params)
    updates, _ = gen_tx.update(zero_grads, state.gen_opt, state.gen_params)
    flat_u = jax.tree_util.tree_leaves(updates)
    flat_p = jax.tree_util.tree_leaves(state.gen_params)
    # pick a leaf with nonzero params (conv kernels always are)
    for u, p in zip(flat_u, flat_p):
        if float(jnp.abs(p).max()) > 1e-3:
            ratio = np.asarray(u) / np.asarray(p)
            np.testing.assert_allclose(
                ratio, -hp.learning_rate * 0.01, rtol=1e-4
            )
            return
    raise AssertionError("no nonzero parameter leaf found")


@pytest.mark.slow
def test_get_vocoder_rejects_full_state_msgpack(tmp_path):
    """Passing the trainer's primary vocoder_*.msgpack (a full VocoderState)
    to get_vocoder must fail with a pointer at the generator sidecar, not an
    opaque deserialization error (regression)."""
    from speakingstyle_tpu.synthesis import get_vocoder
    from speakingstyle_tpu.training.vocoder_trainer import (
        VocoderHParams,
        init_vocoder_state,
        save_vocoder,
    )

    import json as _json

    from speakingstyle_tpu.models.hifigan import Generator

    cfg = Config()
    hp = VocoderHParams(segment_size=SEG)
    state, *_ = init_vocoder_state(
        cfg, hp, jax.random.PRNGKey(0), gen=Generator(**SMALL_GEN),
        **_small_discs(),
    )
    full_path = str(tmp_path / "vocoder_00000001.msgpack")
    gen_path = save_vocoder(full_path, state)
    cfg_json = tmp_path / "config.json"
    cfg_json.write_text(_json.dumps(SMALL_GEN_JSON))
    with pytest.raises(ValueError, match="generator.msgpack"):
        get_vocoder(cfg, full_path, config_path=str(cfg_json))
    # the sidecar still loads fine
    gen2, params2 = get_vocoder(cfg, gen_path, config_path=str(cfg_json))
    assert params2 is not None


@pytest.mark.slow
def test_spectral_norm_sigma_converges_to_true_norm():
    """The first MSD scale's nn.SpectralNorm: after enough power-iteration
    updates, stored sigma matches the true largest singular value of the
    (matricized) conv kernel — the property torch.nn.utils.spectral_norm
    guarantees (reference: hifigan/models.py:185 norm_f selection)."""
    from speakingstyle_tpu.models.hifigan_disc import ScaleDiscriminator

    d = ScaleDiscriminator(use_spectral_norm=True)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((1, 128)), jnp.float32)
    variables = d.init(jax.random.PRNGKey(0), x)

    @jax.jit
    def power_iter(variables):
        _, updates = d.apply(x=x, update_stats=True, mutable=["batch_stats"],
                             variables=variables)
        return {**variables, "batch_stats": updates["batch_stats"]}

    for _ in range(300):  # power iteration to convergence
        variables = power_iter(variables)

    from flax.traverse_util import flatten_dict

    params = flatten_dict(variables["params"], sep="/")
    stats = flatten_dict(variables["batch_stats"], sep="/")
    checked = 0
    cands = [p for p in params if p.endswith("/kernel")]
    for k, sigma in stats.items():
        if not k.endswith("/sigma"):
            continue
        # pair sigma with its conv's kernel by the conv's scope name
        match = [p for p in cands if p.split("/")[-2] in k]
        if not match:
            continue
        w = np.asarray(params[match[0]])
        true_sigma = np.linalg.svd(w.reshape(-1, w.shape[-1]), compute_uv=False)[0]
        np.testing.assert_allclose(float(sigma), true_sigma, rtol=1e-2)
        checked += 1
    assert checked >= 2, "no sigma/kernel pairs matched"


@pytest.mark.slow
def test_train_vocoder_loop_resilience(tmp_path, monkeypatch):
    """The vocoder loop shares the fault-tolerance layer (ISSUE 2):
    nan_grads rolls back to the last saved .msgpack, SIGTERM flushes a
    final checkpoint, and the tail steps always land on disk."""
    import dataclasses
    import os

    import scipy.io.wavfile

    from speakingstyle_tpu.configs.config import ResilienceConfig
    from speakingstyle_tpu.models.hifigan import Generator
    from speakingstyle_tpu.training import faults
    from speakingstyle_tpu.training.vocoder_trainer import (
        VocoderHParams,
        train_vocoder,
    )

    cfg = Config()
    cfg = dataclasses.replace(
        cfg,
        train=dataclasses.replace(
            cfg.train, resilience=ResilienceConfig(max_rollbacks=2)
        ),
    )
    hp = VocoderHParams(segment_size=SEG, learning_rate=1e-4)
    t = np.arange(SEG * 8) / 22050
    wav = (0.5 * np.sin(2 * np.pi * 220 * t) * 30000).astype(np.int16)
    scipy.io.wavfile.write(tmp_path / "a.wav", 22050, wav)
    paths = [str(tmp_path / "a.wav")]
    small = dict(gen=Generator(**SMALL_GEN), **_small_discs())
    ckpt_dir = str(tmp_path / "ck")

    # nan_grads@3 after a save at 2: rollback, then complete 5 steps with
    # the tail (5 % save_every=2 != 0) flushed as a final checkpoint
    monkeypatch.setenv(faults.ENV_VAR, "nan_grads@3")
    state, metrics = train_vocoder(
        cfg, paths, hp=hp, max_steps=5, batch_size=1, ckpt_path=ckpt_dir,
        save_every=2, log_every=1, **small,
    )
    assert int(state.step) == 5
    assert all(np.isfinite(float(v)) for v in metrics.values())
    assert os.path.exists(f"{ckpt_dir}/vocoder_{5:08d}.msgpack")

    # SIGTERM after step 6 (resumed from 5): flush lands at 6, resume
    # completes to 8 with no step gap
    monkeypatch.setenv(faults.ENV_VAR, "sigterm@6")
    state, _ = train_vocoder(
        cfg, paths, hp=hp, max_steps=8, batch_size=1, ckpt_path=ckpt_dir,
        save_every=100, log_every=1,
        restore_path=f"{ckpt_dir}/vocoder_{5:08d}.msgpack", **small,
    )
    assert int(state.step) == 6
    assert os.path.exists(f"{ckpt_dir}/vocoder_{6:08d}.msgpack")
    monkeypatch.delenv(faults.ENV_VAR)
    state, metrics = train_vocoder(
        cfg, paths, hp=hp, max_steps=8, batch_size=1, ckpt_path=ckpt_dir,
        save_every=100, log_every=1,
        restore_path=f"{ckpt_dir}/vocoder_{6:08d}.msgpack", **small,
    )
    assert int(state.step) == 8
    assert all(np.isfinite(float(v)) for v in metrics.values())
