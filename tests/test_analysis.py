"""jaxlint + runtime-contract tests (tier-1 regression gate).

Three layers:
  1. fixture tests — every JL rule has positive (fires) and negative
     (stays silent) snippets, linted in-memory via ``lint_source``;
  2. suppression + baseline mechanics — inline disables, skip-file, and
     the bidirectional baseline compare;
  3. the real gate — the package is clean modulo the committed baseline
     (fails loudly when either the code or the baseline drifts), and the
     CLI exit codes match the contract in ``scripts/lint_jax.py``.
"""

import textwrap

import numpy as np
import pytest

from speakingstyle_tpu.analysis import cli, contracts, linter


def _codes(source, path="speakingstyle_tpu/fake.py"):
    return sorted({f.rule for f in linter.lint_source(
        textwrap.dedent(source), path
    )})


# ---------------------------------------------------------------------------
# JL001 — trace-unsafe control flow
# ---------------------------------------------------------------------------


def test_jl001_positive_if_on_traced_param():
    assert "JL001" in _codes("""
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
    """)


def test_jl001_positive_nn_module_call():
    assert "JL001" in _codes("""
        import flax.linen as nn

        class Layer(nn.Module):
            def __call__(self, x):
                while x < 0:
                    x = x + 1
                return x
    """)


def test_jl001_negative_shape_branch_and_untraced():
    # metadata branches and plain functions are trace-safe
    assert "JL001" not in _codes("""
        import jax

        @jax.jit
        def f(x):
            if x.shape[0] > 2:
                return x[:2]
            return x

        def g(x):
            if x > 0:
                return x
            return -x
    """)


# ---------------------------------------------------------------------------
# JL002 — numpy on jax arrays
# ---------------------------------------------------------------------------

_JL002_SRC = """
    import numpy as np
    import jax.numpy as jnp

    def f():
        y = jnp.ones((3,))
        return np.sum(y)
"""


def test_jl002_positive_np_on_jax_array():
    assert "JL002" in _codes(_JL002_SRC)


def test_jl002_negative_tests_are_exempt():
    assert _codes(_JL002_SRC, path="tests/test_fake.py") == []


def test_jl002_negative_np_on_host_data():
    assert "JL002" not in _codes("""
        import numpy as np
        import jax.numpy as jnp

        def f(host_list):
            y = jnp.ones((3,))
            z = jnp.sum(y)
            return np.sum(host_list), z
    """)


# ---------------------------------------------------------------------------
# JL003 — donation / static hashability
# ---------------------------------------------------------------------------


def test_jl003_positive_missing_donation():
    assert "JL003" in _codes("""
        import jax

        def step(state, batch):
            new_state = state.replace(step=state.step + 1)
            return new_state

        step = jax.jit(step)
    """)


def test_jl003_negative_donated():
    assert "JL003" not in _codes("""
        import jax

        def step(state, batch):
            new_state = state.replace(step=state.step + 1)
            return new_state

        step = jax.jit(step, donate_argnums=(0,))
    """)


def test_jl003_positive_unhashable_static():
    assert "JL003" in _codes("""
        import jax

        def f(x, shapes):
            return x

        g = jax.jit(f, static_argnums=(1,))

        def run(x):
            return g(x, [1, 2])
    """)


# ---------------------------------------------------------------------------
# JL004 — host sync in training loops
# ---------------------------------------------------------------------------

_JL004_SRC = """
    def loop(batches):
        total = 0.0
        for b in batches:
            total += b.loss.item()
        return total
"""


def test_jl004_positive_item_in_training_loop():
    assert "JL004" in _codes(
        _JL004_SRC, path="speakingstyle_tpu/training/fake.py"
    )


def test_jl004_negative_outside_training():
    # same pattern outside training/ is out of scope for this rule
    assert "JL004" not in _codes(
        _JL004_SRC, path="speakingstyle_tpu/ops/fake.py"
    )


def test_jl004_negative_sync_outside_loop():
    assert "JL004" not in _codes("""
        def summarize(final_loss):
            return float(final_loss)
    """, path="speakingstyle_tpu/training/fake.py")


# ---------------------------------------------------------------------------
# JL005 — recompilation hazards
# ---------------------------------------------------------------------------


def test_jl005_positive_config_in_signature():
    assert "JL005" in _codes("""
        import jax

        @jax.jit
        def f(x, cfg):
            return x * cfg.scale
    """)


def test_jl005_positive_dict_param_and_scalar_default():
    codes = linter.lint_source(textwrap.dedent("""
        import jax
        from typing import Dict

        def f(batch: Dict, scale: float = 1.0):
            return batch

        g = jax.jit(f)
    """), "speakingstyle_tpu/fake.py")
    details = {c.detail for c in codes if c.rule == "JL005"}
    assert any("Dict-typed" in d for d in details)
    assert any("scalar param" in d for d in details)


def test_jl005_positive_jit_in_loop():
    assert "JL005" in _codes("""
        import jax

        def main(fns):
            outs = []
            for f in fns:
                outs.append(jax.jit(f))
            return outs
    """)


def test_jl005_negative_static_config():
    assert "JL005" not in _codes("""
        import jax
        import functools

        @functools.partial(jax.jit, static_argnames=("cfg",))
        def f(x, cfg):
            return x * cfg.scale
    """)


# ---------------------------------------------------------------------------
# JL006 — PRNG key reuse
# ---------------------------------------------------------------------------


def test_jl006_positive_key_reuse():
    assert "JL006" in _codes("""
        import jax

        def f(rng):
            a = jax.random.normal(rng, (2,))
            b = jax.random.normal(rng, (2,))
            return a + b
    """)


def test_jl006_positive_key_in_loop():
    assert "JL006" in _codes("""
        import jax

        def f(rng, n):
            out = 0.0
            for _ in range(n):
                out = out + jax.random.normal(rng, (2,))
            return out
    """)


def test_jl006_positive_constant_key_in_traced_context():
    assert "JL006" in _codes("""
        import jax

        @jax.jit
        def f(x):
            k = jax.random.PRNGKey(0)
            return x + jax.random.normal(k, x.shape)
    """)


def test_jl006_negative_split_before_use():
    assert "JL006" not in _codes("""
        import jax

        def f(rng):
            k1, k2 = jax.random.split(rng)
            a = jax.random.normal(k1, (2,))
            b = jax.random.normal(k2, (2,))
            return a + b
    """)


def test_jl006_negative_flax_rngs_dict_idiom():
    # .init/.apply fold the collection name into the key: not reuse
    assert "JL006" not in _codes("""
        def f(model, rng, x):
            return model.init({"params": rng, "dropout": rng}, x)
    """)


# ---------------------------------------------------------------------------
# JL007 — swallowed exceptions
# ---------------------------------------------------------------------------


def test_jl007_positive_broad_except_pass():
    assert "JL007" in _codes("""
        def f(path):
            try:
                return open(path).read()
            except Exception:
                pass
    """)


def test_jl007_positive_bare_except_continue():
    assert "JL007" in _codes("""
        def f(paths):
            out = []
            for p in paths:
                try:
                    out.append(open(p).read())
                except:
                    continue
            return out
    """)


def test_jl007_positive_silent_fallback_value():
    # `except Exception: x = None` swallows just as silently as pass
    assert "JL007" in _codes("""
        def f(raw):
            try:
                data = parse(raw)
            except Exception:
                data = None
            return data
    """)


def test_jl007_negative_specific_exception():
    assert "JL007" not in _codes("""
        def f():
            try:
                import tensorboardX
            except ImportError:
                pass
    """)


def test_jl007_negative_logged_or_reraised():
    assert "JL007" not in _codes("""
        def f(path):
            try:
                return open(path).read()
            except Exception as e:
                print(f"read failed: {e}")
                raise
    """)


def test_jl007_negative_error_is_used():
    # re-packaging the error (e.g. the prefetcher handing it to the
    # consumer thread) is handling, not swallowing
    assert "JL007" not in _codes("""
        def f(q, fn):
            try:
                q.put(fn())
            except Exception as e:
                q.put(e)
    """)


def test_jl007_negative_outside_package():
    assert "JL007" not in _codes("""
        def f(path):
            try:
                return open(path).read()
            except Exception:
                pass
    """, path="tests/fake.py")


# ---------------------------------------------------------------------------
# JL008 — compile in hot path
# ---------------------------------------------------------------------------


def test_jl008_positive_jit_in_loop():
    assert "JL008" in _codes("""
        import jax

        def sweep(variants, x):
            outs = []
            for v in variants:
                f = jax.jit(lambda y: y * v)
                outs.append(f(x))
            return outs
    """)


def test_jl008_positive_aot_chain_in_loop():
    assert "JL008" in _codes("""
        import jax

        def build(fns, args):
            return [jax.jit(f).lower(*args).compile() for f in fns]

        def rebuild_each_step(fn, batches):
            for b in batches:
                exe = jax.jit(fn).lower(b).compile()
                exe(b)
    """)


def test_jl008_positive_jit_in_request_handler():
    # http.server-style do_POST and handle_* names are hot request paths
    assert "JL008" in _codes("""
        import jax

        class Handler:
            def do_POST(self):
                f = jax.jit(self.model_fn)
                return f(self.payload)
    """)
    assert "JL008" in _codes("""
        import jax

        def handle_synthesis(model_fn, payload):
            return jax.jit(model_fn)(payload)
    """)


def test_jl008_negative_module_level_and_startup():
    assert "JL008" not in _codes("""
        import jax

        step = jax.jit(lambda s, b: s + b)

        def serve(batches):
            for b in batches:
                step(1, b)
    """)


def test_jl008_negative_precompile_function_exempt():
    # the sanctioned AOT startup pattern (serving/engine.py)
    assert "JL008" not in _codes("""
        import jax

        def precompile(fn, lattice):
            exes = {}
            for point in lattice:
                exes[point] = jax.jit(fn).lower(point).compile()
            return exes

        def warmup_all(fn, shapes):
            return [jax.jit(fn).lower(s).compile() for s in shapes]
    """)


def test_jl008_negative_re_compile_untouched():
    # only the .lower().compile() AOT chain counts, not other .compile()s
    assert "JL008" not in _codes("""
        import re

        def scan(lines, patterns):
            for p in patterns:
                rx = re.compile(p)
                for ln in lines:
                    rx.match(ln)
    """)


# ---------------------------------------------------------------------------
# JL009 — wall clock used for durations
# ---------------------------------------------------------------------------


def test_jl009_positive_time_time_subtraction():
    assert "JL009" in _codes("""
        import time

        def measure(fn):
            t0 = time.time()
            fn()
            return time.time() - t0
    """)


def test_jl009_positive_from_import_and_alias():
    assert "JL009" in _codes("""
        from time import time

        def measure(fn):
            start = time()
            fn()
            return time() - start
    """)


def test_jl009_positive_stamp_name_subtracted_later():
    assert "JL009" in _codes("""
        import time

        def loop(items):
            began = time.time()
            for it in items:
                handle(it)
            report(elapsed=time.monotonic() - began)
    """)


def test_jl009_negative_monotonic_and_perf_counter():
    assert "JL009" not in _codes("""
        import time

        def measure(fn):
            t0 = time.monotonic()
            fn()
            d1 = time.monotonic() - t0
            t1 = time.perf_counter()
            fn()
            return d1 + (time.perf_counter() - t1)
    """)


def test_jl009_negative_timestamp_only_use():
    # wall time as a *timestamp* (never subtracted) is the sanctioned use
    assert "JL009" not in _codes("""
        import time

        def record(log, event):
            log.emit({"ts": time.time(), "event": event})
    """)


# ---------------------------------------------------------------------------
# JL010 — jitted-call timing without a device sync
# ---------------------------------------------------------------------------


def test_jl010_positive_unsynced_jit_timing():
    assert "JL010" in _codes("""
        import time
        import jax

        def bench(f, x):
            g = jax.jit(f)
            t0 = time.monotonic()
            y = g(x)
            return time.monotonic() - t0
    """)


def test_jl010_positive_aot_compiled_callable():
    assert "JL010" in _codes("""
        import time
        import jax

        def bench(f, x):
            compiled = jax.jit(f).lower(x).compile()
            t0 = time.perf_counter()
            for _ in range(10):
                y = compiled(x)
            dt = time.perf_counter() - t0
            return dt
    """)


def test_jl010_negative_block_until_ready_in_region():
    assert "JL010" not in _codes("""
        import time
        import jax

        def bench(f, x):
            g = jax.jit(f)
            t0 = time.monotonic()
            y = g(x)
            jax.block_until_ready(y)
            return time.monotonic() - t0
    """)


def test_jl010_negative_device_read_in_region():
    # the repo's sanctioned sync idiom: an explicit D2H scalar read
    assert "JL010" not in _codes("""
        import time
        import jax

        def bench(f, x):
            g = jax.jit(f)
            t0 = time.perf_counter()
            for _ in range(10):
                y = g(x)
            float(y)
            return time.perf_counter() - t0
    """)


def test_jl010_negative_non_jitted_timing():
    assert "JL010" not in _codes("""
        import time

        def bench(load):
            t0 = time.monotonic()
            load()
            return time.monotonic() - t0
    """)


# ---------------------------------------------------------------------------
# JL011 — unbounded queues in serving code
# ---------------------------------------------------------------------------

_SERVING_PATH = "speakingstyle_tpu/serving/fake.py"


def test_jl011_positive_unbounded_queue_in_serving():
    assert "JL011" in _codes("""
        import queue

        class Admission:
            def __init__(self):
                self.pending = queue.Queue()
    """, path=_SERVING_PATH)


def test_jl011_positive_zero_maxsize_and_simplequeue():
    src = """
        import queue

        def build():
            a = queue.Queue(maxsize=0)   # stdlib: 0 = infinite
            b = queue.SimpleQueue()      # cannot be bounded at all
            return a, b
    """
    codes = sorted({
        f.detail for f in linter.lint_source(
            textwrap.dedent(src), _SERVING_PATH
        ) if f.rule == "JL011"
    })
    assert len(codes) == 2


def test_jl011_negative_bounded_queue():
    assert "JL011" not in _codes("""
        import queue

        def build(depth):
            a = queue.Queue(maxsize=depth)
            b = queue.PriorityQueue(16)
            return a, b
    """, path=_SERVING_PATH)


def test_jl011_negative_outside_serving():
    # scoped: backpressure is a serving contract; elsewhere an unbounded
    # queue can be a deliberate choice
    assert "JL011" not in _codes("""
        import queue

        q = queue.Queue()
    """, path="speakingstyle_tpu/training/fake.py")


# ---------------------------------------------------------------------------
# JL012 — unbounded caches in serving code
# ---------------------------------------------------------------------------


def test_jl012_positive_dict_cache_in_serving():
    assert "JL012" in _codes("""
        class Frontend:
            def __init__(self):
                self._mel_cache = {}
    """, path=_SERVING_PATH)


def test_jl012_positive_annotated_dict_cache():
    assert "JL012" in _codes("""
        from typing import Dict

        class Frontend:
            def __init__(self):
                self.style_cache: Dict[str, bytes] = dict()
    """, path=_SERVING_PATH)


def test_jl012_positive_lru_cache_maxsize_none_and_functools_cache():
    src = """
        import functools
        from functools import lru_cache

        @lru_cache(maxsize=None)
        def embed(key):
            return key

        @functools.cache
        def lookup(key):
            return key
    """
    details = sorted({
        f.detail for f in linter.lint_source(
            textwrap.dedent(src), _SERVING_PATH
        ) if f.rule == "JL012"
    })
    assert len(details) == 2


def test_jl012_negative_bounded_lru_and_non_cache_dicts():
    # bare lru_cache() keeps the stdlib's bounded default of 128;
    # non-cache-named dicts (routing tables, program maps) are state,
    # not caches — both stay silent
    assert "JL012" not in _codes("""
        from functools import lru_cache

        @lru_cache(maxsize=64)
        def embed(key):
            return key

        @lru_cache()
        def small(key):
            return key

        class Engine:
            def __init__(self):
                self._programs = {}
                self.routes = dict()
    """, path=_SERVING_PATH)


def test_jl012_negative_outside_serving():
    # scoped like JL011: outside serving/ an unbounded memo can be a
    # deliberate choice (e.g. a per-process constant table)
    assert "JL012" not in _codes("""
        class Frontend:
            def __init__(self):
                self._mel_cache = {}
    """, path="speakingstyle_tpu/training/fake.py")


# ---------------------------------------------------------------------------
# JL013 — unbounded blocking waits in serving code
# ---------------------------------------------------------------------------


def test_jl013_positive_bare_result_and_get():
    src = """
        def serve(future, q):
            x = future.result()
            y = q.get()
            return x, y
    """
    details = sorted({
        f.detail for f in linter.lint_source(
            textwrap.dedent(src), _SERVING_PATH
        ) if f.rule == "JL013"
    })
    assert len(details) == 2


def test_jl013_negative_timeout_and_dict_get():
    # timeout= (or a positional deadline) bounds the wait; dict.get(key)
    # carries a positional argument and is not a blocking wait at all
    assert "JL013" not in _codes("""
        def serve(future, q, table):
            x = future.result(timeout=2.5)
            y = q.get(timeout=0.1)
            z = future.result(30)
            return x, y, z, table.get("k"), table.get("k", None)
    """, path=_SERVING_PATH)


def test_jl013_negative_outside_serving():
    # scoped: a training-side collective or a test helper may block
    # deliberately (the process has no request deadline to honor)
    assert "JL013" not in _codes("""
        def gather(future, q):
            return future.result(), q.get()
    """, path="speakingstyle_tpu/training/fake.py")


# ---------------------------------------------------------------------------
# JL014 — hard single-device pinning in training/data code
# ---------------------------------------------------------------------------


def test_jl014_positive_direct_and_via_name():
    src = """
        import jax

        def load(batch):
            dev = jax.local_devices()[0]
            a = jax.device_put(batch, jax.devices()[0])
            b = jax.device_put(batch, device=dev)
            return a, b
    """
    details = sorted({
        f.detail for f in linter.lint_source(
            textwrap.dedent(src), "speakingstyle_tpu/training/fake.py"
        ) if f.rule == "JL014"
    })
    assert details == [
        "device_put pinned to dev",
        "device_put pinned to jax.devices()[...]",
    ]


def test_jl014_positive_under_data_path():
    assert "JL014" in _codes("""
        import jax

        def put(v):
            return jax.device_put(v, jax.devices()[0])
    """, path="speakingstyle_tpu/data/fake.py")


def test_jl014_negative_sharding_device_put():
    # the contract: device_put against a NamedSharding (or no device)
    assert "JL014" not in _codes("""
        import jax

        def put(v, sharding):
            return {"a": jax.device_put(v, sharding), "b": jax.device_put(v)}
    """, path="speakingstyle_tpu/data/fake.py")


def test_jl014_negative_outside_training_and_data():
    # scoped: ops/ kernels and obs/ probes legitimately address one device
    assert "JL014" not in _codes("""
        import jax

        def probe(v):
            return jax.device_put(v, jax.devices()[0])
    """, path="speakingstyle_tpu/ops/fake.py")


# ---------------------------------------------------------------------------
# JL015 — fresh ndarray allocation in the serving hot path
# ---------------------------------------------------------------------------


def test_jl015_positive_alloc_in_dispatch_loop_and_handler():
    src = """
        import numpy as np

        def _dispatch(batch):
            out = []
            for req in batch:
                buf = np.zeros((4, 16), np.float32)
                out.append(np.pad(req, (0, 4)))
            return np.concatenate(out)
    """
    details = sorted({
        f.detail for f in linter.lint_source(
            textwrap.dedent(src), _SERVING_PATH
        ) if f.rule == "JL015"
    })
    assert details == [
        "np.concatenate in dispatch/handler function",
        "np.pad in loop",
        "np.zeros in loop",
    ]


def test_jl015_negative_precompile_and_pool_lease():
    # startup allocation is sanctioned; the steady-state idiom leases a
    # pooled buffer and writes in place
    assert "JL015" not in _codes("""
        import numpy as np

        def precompile(lattice):
            for point in lattice:
                np.zeros(point.shape, np.float32)

        def _dispatch(pool, batch, shape):
            with pool.lease(shape) as buf:
                np.copyto(buf[: len(batch)], 1.0)
                return buf
    """, path=_SERVING_PATH)


def test_jl015_negative_outside_serving():
    # a data loader may build fresh arrays per batch; only the serving
    # hot path carries the allocation-free contract
    assert "JL015" not in _codes("""
        import numpy as np

        def _dispatch(batch):
            for b in batch:
                buf = np.zeros((4,), np.float32)
            return buf
    """, path="speakingstyle_tpu/training/fake.py")


# ---------------------------------------------------------------------------
# JL016 — bare time.sleep in serving loops
# ---------------------------------------------------------------------------


def test_jl016_positive_sleep_in_supervision_loop():
    src = """
        import threading
        import time

        def _supervise(self):
            while not self._stop:
                self._sweep()
                time.sleep(0.25)
    """
    found = [
        f for f in linter.lint_source(textwrap.dedent(src), _SERVING_PATH)
        if f.rule == "JL016"
    ]
    assert len(found) == 1
    assert found[0].detail == "time.sleep in loop"
    assert "Event.wait" in found[0].message


def test_jl016_positive_bare_sleep_import_in_for_loop():
    assert "JL016" in _codes("""
        from time import sleep

        def drain(self, replicas):
            for rep in replicas:
                sleep(0.1)
    """, path=_SERVING_PATH)


def test_jl016_negative_stop_aware_waits_and_one_shot_sleep():
    # the sanctioned idioms: Event.wait / Condition.wait as the loop
    # timer, and a one-shot settle sleep outside any loop
    assert "JL016" not in _codes("""
        import threading
        import time

        def _loop(self):
            while not self._stop.wait(self.interval_s):
                self.step()

        def _supervise(self):
            while True:
                with self._cond:
                    self._cond.wait(timeout=0.25)

        def close(self):
            time.sleep(0.06)
    """, path=_SERVING_PATH)


def test_jl016_negative_outside_serving():
    # bench loops and training backoffs may sleep; only serving-side
    # loops carry the stop-aware contract
    assert "JL016" not in _codes("""
        import time

        def poll(self):
            while self.busy():
                time.sleep(0.01)
    """, path="speakingstyle_tpu/training/fake.py")


# ---------------------------------------------------------------------------
# JL017 — non-atomic persistent writes to artifact paths
# ---------------------------------------------------------------------------

_TRAINING_PATH = "speakingstyle_tpu/training/fake.py"


def test_jl017_positive_open_w_on_manifest_path():
    found = [
        f for f in linter.lint_source(textwrap.dedent("""
            import json

            def save_manifest(manifest_path, data):
                with open(manifest_path, "w") as fh:
                    json.dump(data, fh)
        """), _TRAINING_PATH)
        if f.rule == "JL017"
    ]
    assert len(found) == 1
    assert "non-atomic open" in found[0].detail
    assert "os.replace" in found[0].message


def test_jl017_positive_np_save_on_weights_path():
    assert "JL017" in _codes("""
        import numpy as np

        def snapshot(weights_path, arr):
            np.save(weights_path, arr)
    """, path=_SERVING_PATH)


def test_jl017_positive_mode_keyword():
    assert "JL017" in _codes("""
        def write(ckpt_dir):
            fh = open(ckpt_dir + "/state.json", mode="w")
            fh.close()
    """, path=_TRAINING_PATH)


def test_jl017_negative_temp_then_replace():
    # the sanctioned idiom: write a temp sibling, fsync, os.replace —
    # either the temp marker in the path or the rename in scope clears it
    assert "JL017" not in _codes("""
        import json
        import os

        def save_manifest(manifest_path, data):
            tmp = manifest_path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(data, fh)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, manifest_path)
    """, path=_TRAINING_PATH)


def test_jl017_negative_non_artifact_path_and_read_mode():
    # log files and reads are out of scope; only artifact-shaped names
    # (ckpt / manifest / weights / ...) carry the atomicity contract
    assert "JL017" not in _codes("""
        def dump(log_path, ckpt_path):
            open(log_path, "w").close()
            open(ckpt_path).read()
    """, path=_SERVING_PATH)


def test_jl017_negative_outside_training_serving():
    # bench/analysis scratch writes are exempt: the rule polices the
    # persistent-state subtrees only
    assert "JL017" not in _codes("""
        def save(ckpt_path, blob):
            with open(ckpt_path, "w") as fh:
                fh.write(blob)
    """, path="speakingstyle_tpu/analysis/fake.py")


# ---------------------------------------------------------------------------
# JL018 — XLA compilation outside the program registry
# ---------------------------------------------------------------------------


def test_jl018_positive_jit_call_and_decorator():
    found = _codes("""
        import functools
        import jax

        @jax.jit
        def f(x):
            return x

        @functools.partial(jax.jit, static_argnums=(1,))
        def g(x, n):
            return x * n

        h = jax.jit(lambda y: y)
    """, path="speakingstyle_tpu/serving/fake.py")
    assert "JL018" in found


def test_jl018_positive_from_import_and_aot_chain():
    assert "JL018" in _codes("""
        from jax import jit

        def build(fn, args):
            return fn.lower(*args).compile()
    """, path="speakingstyle_tpu/training/fake.py")
    assert "JL018" in _codes("""
        import jax

        def build(fn):
            return jax.jit(fn)
    """, path="bench.py")


def test_jl018_negative_registry_and_out_of_scope():
    src = """
        import jax

        def compile_it(fn):
            return jax.jit(fn)
    """
    # the one sanctioned file
    assert "JL018" not in _codes(
        src, path="speakingstyle_tpu/parallel/registry.py"
    )
    # tests/scripts are fixtures, not production programs
    assert "JL018" not in _codes(src, path="tests/fake.py")
    assert "JL018" not in _codes(src, path="scripts/fake.py")


def test_jl018_negative_precompile_exempt():
    assert "JL018" not in _codes("""
        import jax

        def precompile(fns):
            return [jax.jit(f) for f in fns]
    """, path="speakingstyle_tpu/serving/fake.py")


def test_jl018_jit_program_is_clean_and_recognized_as_tracing():
    # the sanctioned spelling passes JL018 AND keeps the dataflow rules
    # awake: jit_program-wrapped functions are traced contexts (JL001)
    found = _codes("""
        from speakingstyle_tpu.parallel.registry import jit_program

        @jit_program
        def f(x):
            if x > 0:
                return x
            return -x
    """, path="speakingstyle_tpu/serving/fake.py")
    assert "JL018" not in found
    assert "JL001" in found


def test_jl018_tree_baseline_is_zero():
    """The structural invariant the registry migration bought: no file
    in the enforced tree spells jax.jit / .lower().compile() anymore,
    and none may regress into it (JL018 has NO baseline allowance)."""
    findings = [f for f in linter.lint_paths() if f.rule == "JL018"]
    assert findings == [], (
        "JL018 must stay at zero tree findings — route compiles through "
        f"ProgramRegistry/jit_program: {[f.fingerprint for f in findings]}"
    )


# ---------------------------------------------------------------------------
# JL019 — full-utterance accumulation (append-in-loop + concatenate)
# ---------------------------------------------------------------------------


def test_jl019_positive_append_loop_then_concatenate():
    # the concatenate sits AFTER the loop, so JL015's in-loop test never
    # sees it — this is exactly the spelling JL019 exists for
    src = """
        import numpy as np

        def collect(chunks):
            pieces = []
            for c in chunks:
                pieces.append(c.wav)
            return np.concatenate(pieces)
    """
    found = [
        f for f in linter.lint_source(textwrap.dedent(src), _SERVING_PATH)
        if f.rule == "JL019"
    ]
    assert len(found) == 1
    assert found[0].detail == "np.concatenate(pieces) after loop accumulation"


def test_jl019_positive_jnp_and_extend():
    assert "JL019" in _codes("""
        import jax.numpy as jnp

        def gather(windows):
            mels = []
            while windows:
                mels.extend(windows.pop())
            return jnp.concatenate(mels, axis=0)
    """, path=_SERVING_PATH)


def test_jl019_negative_streaming_yield_and_comprehension():
    # the sanctioned shapes: yield pieces as they are produced, or a
    # concatenate over a comprehension/static list (no loop-grown
    # accumulator — small, bounded, not utterance-scale)
    assert "JL019" not in _codes("""
        import numpy as np

        def stream(chunks):
            for c in chunks:
                yield c.wav

        def pack(rows):
            return np.concatenate([r.head for r in rows])
    """, path=_SERVING_PATH)


def test_jl019_negative_scope_and_path():
    # a list grown in ONE function and concatenated in another is not
    # the pattern (the accumulator never coexists with the concat), and
    # non-serving code may accumulate freely
    assert "JL019" not in _codes("""
        import numpy as np

        def grow(chunks):
            pieces = []
            for c in chunks:
                pieces.append(c)
            return pieces

        def join(pieces):
            return np.concatenate(pieces)
    """, path=_SERVING_PATH)
    assert "JL019" not in _codes("""
        import numpy as np

        def collect(chunks):
            pieces = []
            for c in chunks:
                pieces.append(c)
            return np.concatenate(pieces)
    """, path="speakingstyle_tpu/training/fake.py")


def test_jl019_negative_precompile_exempt():
    assert "JL019" not in _codes("""
        import numpy as np

        def precompile(points):
            shapes = []
            for p in points:
                shapes.append(np.zeros(p))
            return np.concatenate(shapes)
    """, path=_SERVING_PATH)


def test_jl019_tree_baseline_is_zero():
    """The long-form subsystem's bounded-memory claim, structurally: no
    serving file accumulates-then-concatenates a full utterance (the
    Stitcher holds one crossfade tail; streaming emits windows)."""
    findings = [f for f in linter.lint_paths() if f.rule == "JL019"]
    assert findings == [], (
        "JL019 must stay at zero tree findings — stream pieces instead "
        f"of rebuilding utterances: {[f.fingerprint for f in findings]}"
    )


# ---------------------------------------------------------------------------
# JL024 — wire calls without an explicit timeout in serving code
# ---------------------------------------------------------------------------


def test_jl024_positive_each_wire_primitive():
    src = """
        import socket
        import urllib.request
        from http.client import HTTPConnection
        import requests

        def register(host, port, url):
            conn = HTTPConnection(host, port)
            page = urllib.request.urlopen(url)
            resp = requests.post(url, json={"ready": True})
            raw = socket.create_connection((host, port))
            return conn, page, resp, raw
    """
    found = [
        f for f in linter.lint_source(textwrap.dedent(src), _SERVING_PATH)
        if f.rule == "JL024"
    ]
    assert len(found) == 4
    assert {f.detail.split("(")[0] for f in found} == {
        "HTTPConnection", "urllib.request.urlopen", "requests.post",
        "socket.create_connection",
    }


def test_jl024_negative_bounded_calls():
    # the sanctioned shapes: timeout= keyword anywhere, or the
    # positional timeout slot filled (HTTPConnection's third arg,
    # urlopen's third, create_connection's second)
    assert "JL024" not in _codes("""
        import socket
        import urllib.request
        from http.client import HTTPConnection
        import requests

        def register(host, port, url, budget_s):
            conn = HTTPConnection(host, port, timeout=budget_s)
            pos = HTTPConnection(host, port, budget_s)
            page = urllib.request.urlopen(url, None, budget_s)
            resp = requests.post(url, json={}, timeout=budget_s)
            raw = socket.create_connection((host, port), budget_s)
            return conn, pos, page, resp, raw
    """, path=_SERVING_PATH)


def test_jl024_negative_scope_and_lookalikes():
    # non-serving code may rely on defaults (offline tooling), and a
    # LOCAL helper that happens to be named create_connection is not
    # the socket primitive
    src = """
        from http.client import HTTPConnection

        def fetch(host, port):
            return HTTPConnection(host, port)
    """
    assert "JL024" not in _codes(
        src, path="speakingstyle_tpu/training/fake.py"
    )
    assert "JL024" not in _codes("""
        def probe(pool, addr):
            return pool.create_connection(addr)
    """, path=_SERVING_PATH)


def test_jl024_tree_baseline_is_zero():
    """The control plane's bounded-wire claim, structurally: every
    dispatch, heartbeat, registration, and adoption probe in serving/
    passes an explicit timeout (lease/breaker/hedge budgets assume wire
    attempts fail in bounded time)."""
    findings = [f for f in linter.lint_paths() if f.rule == "JL024"]
    assert findings == [], (
        "JL024 must stay at zero tree findings — pass timeout= at every "
        f"serving wire call: {[f.fingerprint for f in findings]}"
    )


# ---------------------------------------------------------------------------
# JL025 — weight-tree precision casts outside the sanctioned helper
# ---------------------------------------------------------------------------


def test_jl025_positive_each_cast_shape():
    src = """
        import jax
        import jax.numpy as jnp

        def shrink(variables, state, teacher_variables):
            a = variables.astype(jnp.bfloat16)
            b = jnp.float32(state.params)
            c = jax.tree_util.tree_map(
                lambda x: x.astype(jnp.bfloat16), teacher_variables)
            return a, b, c
    """
    found = [
        f for f in linter.lint_source(textwrap.dedent(src), _SERVING_PATH)
        if f.rule == "JL025"
    ]
    assert len(found) == 3
    assert all("weight-tree cast" in f.detail for f in found)


def test_jl025_negative_registry_is_sanctioned():
    # the ONE place weight casts are allowed: the cast_params /
    # dequant_params choke point itself
    src = """
        import jax.numpy as jnp

        def cast_params(variables, precision):
            return variables.astype(jnp.bfloat16)
    """
    assert "JL025" not in _codes(
        src, path="speakingstyle_tpu/parallel/registry.py"
    )


def test_jl025_negative_activation_and_nonweight_casts():
    # activations, mels, and non-weight trees cast freely — the rule
    # keys on params/variables naming, not on astype itself
    assert "JL025" not in _codes("""
        import jax
        import jax.numpy as jnp

        def fwd(x, mel, batch):
            y = x.astype(jnp.bfloat16)
            w = mel.astype(jnp.float32)
            z = jax.tree_util.tree_map(lambda a: a.astype(jnp.float32), batch)
            return y, w, z
    """, path=_SERVING_PATH)


def test_jl025_tree_baseline_is_zero():
    """The precision-governance claim, structurally: every weight-tree
    cast in the package flows through cast_params in
    parallel/registry.py, so the registry cache key / ProgramCards /
    tier gates see every precision that serves."""
    findings = [f for f in linter.lint_paths() if f.rule == "JL025"]
    assert findings == [], (
        "JL025 must stay at zero tree findings — route weight-tree casts "
        f"through cast_params: {[f.fingerprint for f in findings]}"
    )


# ---------------------------------------------------------------------------
# JL026 — label-cardinality bombs at metric registration sites
# ---------------------------------------------------------------------------


def test_jl026_positive_each_bomb_shape():
    # per-request identity in a label value (direct, attribute,
    # f-string, subscript) and in a dynamic metric name
    src = """
        def handle(self, registry, req_id, payload, r):
            registry.counter("serve_requests_total",
                             labels={"req": req_id}).inc()
            registry.gauge("serve_inflight",
                           labels={"trace": r.trace_id}).set(1)
            registry.histogram("serve_latency_seconds",
                               labels={"who": f"{payload['text']}"})
            registry.counter(f"serve_{req_id}_total").inc()
    """
    found = [
        f for f in linter.lint_source(textwrap.dedent(src), _SERVING_PATH)
        if f.rule == "JL026"
    ]
    assert len(found) == 4
    details = " | ".join(f.detail for f in found)
    assert "req_id" in details and "trace_id" in details
    assert "the metric name" in details


def test_jl026_negative_bounded_labels_and_other_receivers():
    # bounded dynamic labels (class/replica/reason/bucket) are the
    # sanctioned idiom; non-registry receivers and non-serving paths
    # are out of scope
    assert "JL026" not in _codes("""
        def dispatch(self, registry, klass, rid, reason):
            registry.counter("serve_class_requests_total",
                             labels={"class": klass}).inc()
            registry.gauge("serve_replica_busy",
                           labels={"replica": rid}).set(1)
            registry.counter("serve_autoscale_decisions_total",
                             labels={"reason": reason}).inc()
    """, path=_SERVING_PATH)
    assert "JL026" not in _codes("""
        def tally(self, counters, req_id):
            counters.counter("x", labels={"req": req_id})
    """, path=_SERVING_PATH)
    assert "JL026" not in _codes("""
        def tally(self, registry, req_id):
            registry.counter("x", labels={"req": req_id})
    """, path="speakingstyle_tpu/training/fake.py")


def test_jl026_tree_baseline_is_zero():
    """The bounded-cardinality claim, structurally: every metric label
    in serving/ and obs/ is a bounded vocabulary — per-request identity
    rides spans and events, so /metrics stays O(config), not
    O(traffic)."""
    findings = [f for f in linter.lint_paths() if f.rule == "JL026"]
    assert findings == [], (
        "JL026 must stay at zero tree findings — per-request identity "
        f"goes on spans/events, not labels: "
        f"{[f.fingerprint for f in findings]}"
    )


# ---------------------------------------------------------------------------
# JL027 — audio bytes leaving serving code without the quality choke point
# ---------------------------------------------------------------------------


def test_jl027_positive_each_emission_shape():
    # the three emission spellings: float->int16 PCM conversion, RIFF
    # container build, audio-named buffer serialization — each in a
    # function with no validator evidence
    src = """
        import numpy as np

        def collect(self, wav_f):
            wav = wav_f.astype(np.int16)
            return wav

        def container(wav):
            return wav_bytes(wav, 22050)

        def push(self, chunk):
            self.sock.send(chunk.tobytes())
    """
    found = [
        f for f in linter.lint_source(textwrap.dedent(src), _SERVING_PATH)
        if f.rule == "JL027"
    ]
    assert len(found) == 3
    details = " | ".join(f.detail for f in found)
    assert ".astype(int16)" in details
    assert "wav_bytes(...)" in details
    assert "chunk.tobytes()" in details


def test_jl027_negative_validated_paths_and_scope():
    # a quality-gate call in the same function sanctions its emissions
    assert "JL027" not in _codes("""
        import numpy as np

        def collect(self, wav_f, klass):
            wav = wav_f.astype(np.int16)
            self.quality.check(wav, klass=klass, source="stream")
            return wav
    """, path=_SERVING_PATH)
    # validator evidence in an ENCLOSING function sanctions a helper
    # closure's emission (the handler validated what the closure ships)
    assert "JL027" not in _codes("""
        import numpy as np

        def handler(self, wav_f):
            def ship(w):
                return w.astype(np.int16)
            validate_wav(wav_f, 22050, self.qcfg)
            return ship(wav_f)
    """, path=_SERVING_PATH)
    # a generic buffer serialization is not audio; non-serving paths
    # are out of scope
    assert "JL027" not in _codes("""
        def pack(a):
            return a.tobytes()
    """, path=_SERVING_PATH)
    assert "JL027" not in _codes("""
        import numpy as np

        def collect(wav_f):
            return wav_f.astype(np.int16)
    """, path="speakingstyle_tpu/training/fake.py")


def test_jl027_tree_baseline_is_zero():
    """The every-wav-crosses-the-gate claim, structurally: each audio
    emission site in serving/ sits in a function that also passes the
    buffer through obs/quality.py — so the validators, the quality SLO
    stream, and the golden-probe drill see every path."""
    findings = [f for f in linter.lint_paths() if f.rule == "JL027"]
    assert findings == [], (
        "JL027 must stay at zero tree findings — every audio emission "
        f"goes through the quality choke point: "
        f"{[f.fingerprint for f in findings]}"
    )


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

_SUPPRESSIBLE = """
    import jax

    @jax.jit
    def f(x):
        if x > 0:{comment}
            return x
        return -x
"""


def test_inline_disable_specific_rule():
    src = _SUPPRESSIBLE.format(comment="  # jaxlint: disable=JL001")
    assert "JL001" not in _codes(src)


def test_inline_disable_bare():
    src = _SUPPRESSIBLE.format(comment="  # jaxlint: disable")
    assert "JL001" not in _codes(src)


def test_inline_disable_other_rule_does_not_apply():
    src = _SUPPRESSIBLE.format(comment="  # jaxlint: disable=JL004")
    assert "JL001" in _codes(src)


def test_skip_file_directive():
    src = "# jaxlint: skip-file\n" + textwrap.dedent(
        _SUPPRESSIBLE.format(comment="")
    )
    assert linter.lint_source(src, "speakingstyle_tpu/fake.py") == []


def test_directive_in_string_literal_is_ignored():
    src = 's = "# jaxlint: skip-file"\n' + textwrap.dedent(
        _SUPPRESSIBLE.format(comment="")
    )
    assert "JL001" in {
        f.rule for f in linter.lint_source(src, "speakingstyle_tpu/fake.py")
    }


# ---------------------------------------------------------------------------
# baseline mechanics + the real gate
# ---------------------------------------------------------------------------


def test_baseline_compare_is_bidirectional():
    findings = linter.lint_source(textwrap.dedent("""
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
    """), "speakingstyle_tpu/fake.py")
    assert findings
    empty = linter.findings_counter([])
    new, stale = linter.compare_to_baseline(findings, empty)
    assert new and not stale
    new, stale = linter.compare_to_baseline(
        [], linter.findings_counter(findings)
    )
    assert stale and not new


def test_baseline_roundtrip(tmp_path):
    findings = linter.lint_source(
        "import jax\n\n@jax.jit\ndef f(x):\n    if x > 0:\n        return x"
        "\n    return -x\n",
        "speakingstyle_tpu/fake.py",
    )
    path = str(tmp_path / "baseline.json")
    linter.save_baseline(findings, path)
    loaded = linter.load_baseline(path)
    new, stale = linter.compare_to_baseline(findings, loaded)
    assert not new and not stale


def test_repo_is_clean_modulo_committed_baseline():
    """THE tier-1 gate: the tree must match analysis/baseline.json exactly.

    New findings => fix them or (if deliberate) run
    `python scripts/lint_jax.py --update-baseline` and commit the diff.
    Stale entries => the hazard was fixed; update the baseline so it
    cannot mask a future regression at the same fingerprint.
    """
    findings = linter.lint_paths()
    baseline = linter.load_baseline()
    assert baseline, "committed baseline is missing or empty"
    new, stale = linter.compare_to_baseline(findings, baseline)
    assert not new, (
        "new jaxlint findings over the committed baseline "
        f"(run scripts/lint_jax.py to see them): {sorted(new)}"
    )
    assert not stale, (
        "stale baseline entries (fixed in code, still listed — run "
        f"scripts/lint_jax.py --update-baseline): {sorted(stale)}"
    )


def test_every_rule_is_non_vacuous():
    """Each JL rule has at least one true finding in the tree (possibly
    baselined) — rules that never fire are dead weight."""
    fired = {f.rule for f in linter.lint_paths()}
    fired |= {fp.split(":", 1)[0] for fp in linter.load_baseline()}
    # JL009–JL012 are deliberately absent: the tree already follows the
    # monotonic-clock duration discipline, syncs (reads a device value
    # back) inside every jit-timing region, bounds every serving queue,
    # AND bounds every serving cache (the StyleService LRU replaced the
    # frontend's unbounded per-path mel dict), so there is nothing to
    # baseline — the desired steady state for preventive rules; their
    # fixtures above keep them non-vacuous. JL013 fires on the real tree
    # via its one baselined hit (the batcher's condition-protected
    # collect wait), so it is covered by the baseline union below.
    # JL014 is likewise deliberately absent: training/ and data/ already
    # device_put against NamedShardings only (the hard pins that remain
    # live in ops/ and obs/, outside the rule's scope on purpose).
    # JL015 is absent because the PR that added it also moved every
    # dispatch-loop staging allocation onto the BufferPool — the rule
    # exists to keep it that way. JL016 is absent because every serving
    # loop already parks stop-aware (the fleet supervisor on its
    # Condition, the autoscaler on its Event) — the remaining sleeps
    # are one-shot (close settle, injected-fault stall), outside loops.
    # JL017 is absent because the one in-scope artifact writer (the
    # checkpoint manifest in training/checkpoint.py) already publishes
    # via temp + fsync + os.replace — the idiom the rule enforces.
    # JL018 is absent BY CONSTRUCTION: the registry migration removed
    # every jax.jit / .lower().compile() spelling from the enforced
    # tree, and test_jl018_tree_baseline_is_zero pins it at zero.
    # JL019 is likewise absent by construction: the long-form subsystem
    # was written streaming-first (Stitcher seams, window yields), and
    # test_jl019_tree_baseline_is_zero pins the accumulate-then-concat
    # count at zero.
    # JL024 is absent by construction too: the cluster tier that made
    # serving/ a wire client shipped with an explicit timeout on every
    # HTTP/socket call (derived from deadline budgets or
    # connect_timeout_s), and test_jl024_tree_baseline_is_zero pins the
    # unbounded-wire count at zero.
    # JL025 is absent by construction as well: the precision lattice
    # shipped with cast_params/dequant_params as the only weight-cast
    # spellings in the tree (the rule exists to keep every future cast
    # inside that choke point), and test_jl025_tree_baseline_is_zero
    # pins the out-of-band count at zero.
    for code in ("JL001", "JL002", "JL003", "JL004", "JL005", "JL006",
                 "JL007", "JL008"):
        assert code in fired, f"{code} never fires on the real tree"


def test_cli_check_exits_zero_on_repo():
    assert cli.main(["--check"]) == 0


@pytest.mark.parametrize("code,src", [
    ("JL001", "import jax\n\n@jax.jit\ndef f(x):\n    if x > 0:\n"
              "        return x\n    return -x\n"),
    ("JL002", "import numpy as np\nimport jax.numpy as jnp\n\ndef f():\n"
              "    y = jnp.ones((3,))\n    return np.sum(y)\n"),
    ("JL003", "import jax\n\ndef step(state, b):\n"
              "    new_state = state.replace(step=state.step + 1)\n"
              "    return new_state\n\nstep = jax.jit(step)\n"),
    ("JL004", "def loop(bs):\n    t = 0.0\n    for b in bs:\n"
              "        t += b.loss.item()\n    return t\n"),
    ("JL005", "import jax\n\n@jax.jit\ndef f(x, cfg):\n"
              "    return x * cfg.scale\n"),
    ("JL006", "import jax\n\ndef f(rng):\n"
              "    a = jax.random.normal(rng, (2,))\n"
              "    b = jax.random.normal(rng, (2,))\n    return a + b\n"),
    ("JL007", "def f(p):\n    try:\n        return open(p).read()\n"
              "    except Exception:\n        pass\n"),
    ("JL008", "import jax\n\ndef sweep(vs, x):\n    for v in vs:\n"
              "        jax.jit(lambda y: y * v)(x)\n"),
    ("JL010", "import time\nimport jax\n\ndef bench(f, x):\n"
              "    g = jax.jit(f)\n    t0 = time.monotonic()\n"
              "    y = g(x)\n    return time.monotonic() - t0\n"),
    ("JL011", "import queue\n\nq = queue.Queue()\n"),
    ("JL012", "class F:\n    def __init__(self):\n"
              "        self._mel_cache = {}\n"),
    ("JL013", "def serve(future):\n    return future.result()\n"),
    ("JL014", "import jax\n\ndef put(v):\n"
              "    return jax.device_put(v, jax.devices()[0])\n"),
    ("JL015", "import numpy as np\n\ndef handle(reqs):\n    for r in reqs:\n"
              "        buf = np.zeros((8,), np.float32)\n"),
    ("JL016", "import time\n\ndef _supervise(self):\n    while True:\n"
              "        time.sleep(0.25)\n"),
    ("JL017", "def save(ckpt_path, blob):\n"
              "    with open(ckpt_path, \"w\") as fh:\n"
              "        fh.write(blob)\n"),
    ("JL018", "import jax\n\ndef build(fn):\n    return jax.jit(fn)\n"),
    ("JL019", "import numpy as np\n\ndef collect(chunks):\n    out = []\n"
              "    for c in chunks:\n        out.append(c)\n"
              "    return np.concatenate(out)\n"),
    ("JL024", "from http.client import HTTPConnection\n\ndef ping(host):\n"
              "    return HTTPConnection(host, 80)\n"),
    ("JL025", "import jax.numpy as jnp\n\ndef shrink(variables):\n"
              "    return variables.astype(jnp.bfloat16)\n"),
    ("JL026", "def handle(registry, req_id):\n"
              "    registry.counter(\"serve_requests_total\",\n"
              "                     labels={\"req\": req_id}).inc()\n"),
    ("JL027", "import numpy as np\n\ndef collect(wav_f):\n"
              "    return wav_f.astype(np.int16)\n"),
])
def test_cli_exits_nonzero_on_each_positive_fixture(tmp_path, code, src):
    # JL004 is scoped to training/ paths; JL007 to speakingstyle_tpu/;
    # JL011-JL013, JL015, JL016, JL019 and JL024 to
    # speakingstyle_tpu/serving/; JL017 to both training/ and serving/
    # (training default suffices)
    sub = ("serving" if code in ("JL011", "JL012", "JL013", "JL015", "JL016",
                                 "JL019", "JL024", "JL026", "JL027")
           else "training")
    d = tmp_path / "speakingstyle_tpu" / sub
    d.mkdir(parents=True)
    f = d / "fixture.py"
    f.write_text(src)
    rc = cli.main([str(f), "--no-baseline", "--check", "--select", code])
    assert rc == 1, f"{code} positive fixture did not fail the CLI"


def test_cli_rejects_unknown_rule():
    assert cli.main(["--select", "JL999"]) == 2


def test_cli_list_rules():
    assert cli.main(["--list-rules"]) == 0


# ---------------------------------------------------------------------------
# runtime contracts
# ---------------------------------------------------------------------------


def test_contracts_noop_when_disabled(monkeypatch):
    monkeypatch.setattr(contracts, "ENABLED", False)
    x = np.zeros((2, 3))
    assert contracts.assert_shape(x, (99, 99), "x") is x
    assert contracts.assert_rank(x, 7, "x") is x
    assert contracts.assert_dtype(x, "integer", "x") is x
    assert contracts.assert_tree_finite(
        {"a": np.array([np.nan])}, "t"
    ) is not None


def test_contracts_enabled(monkeypatch):
    monkeypatch.setattr(contracts, "ENABLED", True)
    x = np.zeros((2, 3), np.float32)
    # passing specs return the array through
    assert contracts.assert_shape(x, (2, 3), "x") is x
    assert contracts.assert_shape(x, (None, 3), "x") is x
    assert contracts.assert_rank(x, 2, "x") is x
    assert contracts.assert_dtype(x, "floating", "x") is x
    assert contracts.assert_shape(None, (1,), "optional") is None
    with pytest.raises(contracts.ContractError):
        contracts.assert_shape(x, (2, 4), "x")
    with pytest.raises(contracts.ContractError):
        contracts.assert_rank(x, 3, "x")
    with pytest.raises(contracts.ContractError):
        contracts.assert_dtype(x, "integer", "x")
    with pytest.raises(contracts.ContractError):
        contracts.assert_tree_finite({"a": np.array([1.0, np.nan])}, "t")
    contracts.assert_tree_finite({"a": np.array([1.0, 2.0])}, "t")


def test_contracts_tree_finite_skips_tracers(monkeypatch):
    monkeypatch.setattr(contracts, "ENABLED", True)
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        contracts.assert_tree_finite({"x": x}, "inside-jit")
        return x * 2

    # NaN input must NOT raise inside jit (leaves are tracers there);
    # the check belongs at host boundaries
    out = f(jnp.array([jnp.nan]))
    assert np.isnan(np.asarray(out)).all()


def test_contracts_fire_at_trace_time_in_jit(monkeypatch):
    monkeypatch.setattr(contracts, "ENABLED", True)
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        contracts.assert_rank(x, 2, "x")
        return x

    with pytest.raises(contracts.ContractError):
        f(jnp.zeros((3,)))  # wrong rank fails during tracing


def test_length_regulate_contract_integration(monkeypatch):
    monkeypatch.setattr(contracts, "ENABLED", True)
    import jax.numpy as jnp

    from speakingstyle_tpu.ops.length_regulator import length_regulate

    x = jnp.zeros((2, 5, 8))
    good = jnp.ones((2, 5), jnp.int32)
    frames, lens, mask = length_regulate(x, good, 16)
    assert frames.shape == (2, 16, 8)
    with pytest.raises(contracts.ContractError):
        length_regulate(x, jnp.ones((2, 4), jnp.int32), 16)
    with pytest.raises(contracts.ContractError):
        length_regulate(x[0], good, 16)  # rank-2 features
